"""Benchmark: query throughput + p50 latency vs the reference baseline.

Reference baseline (BASELINE.md / ``html/faq.html:320``): ~8 queries/sec
on a 10M-page index on 2010-era hardware (dual quad-core, 8 gb
instances). BASELINE.json's measurable config: conjunctive AND +
single-term queries on one chip — the ``PosdbTable::intersectLists10_r``
path (two-phase device kernel) plus the host plan (Msg2 equivalent).

Honesty notes:
* the corpus is built through the REAL indexing pipeline (HTML →
  tokenizer → posdb keys → Rdb), then dumped so the measured queries
  exercise the on-disk base path (dense impact rows + materialized cube
  rows + a small live delta) — not a memtable-only toy;
* every measured query string is UNIQUE — the tunneled TPU backend can
  serve repeated identical dispatches from a cache, which would fake
  the throughput number;
* p50 single-query latency is measured on warmed shape buckets
  (compiles excluded; the cache warmup cost is reported on stderr).

Prints exactly ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``.

Scale line (BENCH_DOCS=250000 — 62.9M stored postings, 94% of the
2^26 per-shard posting cap, the "split across shards" design point):
measured 8.0 qps, p50 392 ms on one v5e chip (2026-07-30; the
full-corpus exact kernels are O(D) per query, so per-query cost grows
with the shard and the HBM budget shrinks wave batching — the
multi-shard mesh, not a bigger shard, is the scaling axis, exactly as
the reference splits at ~500k pages per host).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_QPS = 8.0  # html/faq.html:320

N_DOCS = int(os.environ.get("BENCH_DOCS", "100000"))
N_QUERIES = int(os.environ.get("BENCH_QUERIES", "512"))
BATCH = int(os.environ.get("BENCH_BATCH", "64"))
N_LAT = int(os.environ.get("BENCH_LAT_QUERIES", "64"))
VOCAB = 2000


def _load_scale() -> dict:
    """This machine's cache of measured runs (one entry per corpus
    size, latest wins)."""
    scale_path = os.path.expanduser("~/.cache/osse_bench_scale.json")
    try:
        with open(scale_path) as f:
            return json.load(f)
    except Exception:
        return {}


def _curve_of(scale: dict) -> list[dict]:
    # each point carries the commit + replay size it was measured at —
    # the cache spans runs, and a curve must not pass off stale or
    # smoke-sized points as current
    return [{"docs": int(d), **{k: v.get(k) for k in
                                ("qps", "p50_ms", "recall_at_10",
                                 "recall_queries", "replay_n",
                                 "commit")}}
            for d, v in sorted(scale.items(), key=lambda kv:
                               int(kv[0]))
            if int(d) >= 10000]  # smoke-sized runs aren't the curve


def _init_backend(max_tries: int = 3):
    """Backend init with bounded retry-with-backoff — the tunneled TPU
    client's first device enumeration is the observed wedge point, and
    transient RPC failures there must not burn a whole bench run.
    Returns the jax module; raises the last error once retries are
    exhausted (callers then emit the cached curve, see
    _emit_stale_curve)."""
    last: Exception | None = None
    base = float(os.environ.get("BENCH_INIT_BACKOFF_S", "5"))
    for attempt in range(max_tries):
        try:
            import jax
            jax.devices()  # forces backend client init
            return jax
        except Exception as e:  # noqa: BLE001 — any init failure
            last = e
            wait = base * (2 ** attempt)
            print(f"# backend init failed "
                  f"(attempt {attempt + 1}/{max_tries}): {e}; "
                  f"retrying in {wait}s", file=sys.stderr)
            try:  # drop the poisoned client so the retry re-inits
                import jax.extend.backend
                jax.extend.backend.clear_backends()
            except Exception:
                pass
            time.sleep(wait)
    raise last  # type: ignore[misc]


def _backend_record() -> dict:
    """The resolved JAX backend stamped into every BENCH_* JSON line —
    a TPU-measured point and a CPU-fallback point must never be
    confused when curves span runs. ``device_measured`` is True only
    when the run actually resolved a TPU backend; a CPU fallback (or a
    backend that never initialized) marks the numbers host-measured."""
    try:
        import jax
        backend = jax.default_backend()
        rec = {"backend": str(backend),
               "device_measured": str(backend) == "tpu"}
    except Exception:  # noqa: BLE001 — backend never initialized
        rec = {"backend": "none", "device_measured": False}
    try:  # doctor stamp: jax version, device kind/count, topology,
        # memory_stats (null on CPU) — the r05 post-mortem's ask
        from tools import devdoctor
        rec.update(devdoctor.stamp())
    except Exception:  # noqa: BLE001 — stamp must never break a leg
        pass
    return rec


def _emit_stale_curve(reason: str) -> None:
    """Persistent backend failure: print the last-good cached scale
    curve marked ``"stale": true`` and exit 0 — a parseable
    degraded answer instead of rc=1 with no JSON line (which reads
    as a wedged bench and discards every prior measurement)."""
    curve = _curve_of(_load_scale())
    latest = curve[-1] if curve else {}
    qps = latest.get("qps") or 0.0
    print(json.dumps({
        "metric": "queries_per_sec",
        "value": round(qps, 2),
        "unit": "qps",
        "vs_baseline": round(qps / BASELINE_QPS, 2),
        "stale": True,
        **_backend_record(),
        "device_measured": False,  # cached numbers, not this run's
        "error": reason[:300],
        "docs": latest.get("docs", 0),
        "scale": curve,
    }))
    print(f"# backend unavailable ({reason[:120]}); emitted last-good "
          "cached curve", file=sys.stderr)


def _gen_docs(n_docs: int):
    """Synthetic zipf-vocabulary HTML corpus (deterministic)."""
    import numpy as np

    rng = np.random.default_rng(42)
    varr = np.array([f"word{i}" for i in range(VOCAB)])
    for d in range(n_docs):
        n_words = int(rng.integers(60, 220))
        idx = rng.zipf(1.35, size=n_words) % VOCAB
        words = varr[idx]
        title = " ".join(words[:4])
        sents = [" ".join(words[s:s + 12]) + "." for s in
                 range(0, n_words, 12)]
        yield (f"http://site{d % 97}.bench.test/doc{d}",
               f"<html><head><title>{title}</title></head><body><p>"
               + " ".join(sents) + "</p></body></html>")


def _make_queries(n: int, seed: int):
    """n UNIQUE 1-3 term zipf queries (BASELINE configs 1-2)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    seen: set[str] = set()
    out: list[str] = []
    while len(out) < n:
        n_terms = int(rng.integers(1, 4))
        terms = rng.zipf(1.3, size=n_terms) % VOCAB
        q = " ".join(f"word{t}" for t in terms)
        if q not in seen:
            seen.add(q)
            out.append(q)
    return out


def _mesh_build_sc(bdir: str, n_shards: int, n_docs: int,
                   n_replicas: int = 1):
    """Build (or reuse) a sharded bench corpus through the real
    indexing pipeline, dumped so queries serve from the on-disk base."""
    from open_source_search_engine_tpu.parallel.sharded import \
        ShardedCollection
    sc = ShardedCollection("bench", bdir, n_shards=n_shards,
                           n_replicas=n_replicas)
    for row in sc.grid:
        for c in row:
            c.conf.pqr_enabled = False
    if sc.num_docs < n_docs:
        for url, html in _gen_docs(n_docs):
            sc.index_document(url, html)
        for row in sc.grid:
            for shard in row:
                shard.posdb.dump()
                shard.titledb.dump()
                shard.save()
    return sc


def _mesh_jit_leg(mr) -> dict:
    """The trace-discipline leg of the mesh gate: 64 steady-state mesh
    waves with VARYING (bucketed) batch sizes under the jit watcher —
    zero compiles, zero retraces, and the only transfers on the wave
    boundary (the device_put at issue + the one device_get at collect,
    both in parallel/sharded.py, a jitwatch BOUNDARY_SITE). This is
    the machine proof that nothing crosses the host between shard
    intersection and merged top-k."""
    from open_source_search_engine_tpu.query import engine
    from open_source_search_engine_tpu.utils import jitwatch
    msi = mr._serve_index()
    plans = [engine._compile_cached(q, 0)
             for q in _make_queries(16, seed=11)]
    jitwatch.enable()
    # warm every live batch bucket once (compiles excluded from gate)
    for b in (3, 8, 16):
        msi.collect_batch(msi.issue_batch(plans[:b], topk=10))
    jitwatch.reset()
    n_waves = int(os.environ.get("BENCH_MESH_JIT_WAVES", "64"))
    # deterministic varying sizes: buckets 4/8/16 revisited, never new
    sizes = [16, 5, 9, 16, 3, 12, 8, 16]
    t0 = time.perf_counter()
    for k in range(n_waves):
        b = sizes[k % len(sizes)]
        msi.collect_batch(msi.issue_batch(plans[:b], topk=10))
    dt = time.perf_counter() - t0
    snap = jitwatch.snapshot()
    jitwatch.disable()
    t = snap["totals"]
    offb = [e["site"] for e in snap["events"]
            if e["kind"] == "transfer" and not e["boundary"]]
    return {"waves": n_waves,
            "wave_ms": round(1000 * dt / n_waves, 2),
            "compiles": t["compiles"], "retraces": t["retraces"],
            "transfers_offboundary": t["transfers_offboundary"],
            "offboundary_sites": offb,
            "ok": (t["compiles"] == 0 and t["retraces"] == 0
                   and t["transfers_offboundary"] == 0)}


def _mesh_child() -> None:
    """One curve point, run in a subprocess so XLA_FLAGS can force its
    own host device count before jax imports. Config rides the
    BENCH_MESH_CHILD env as JSON; emits one JSON line on stdout."""
    cfg = json.loads(os.environ["BENCH_MESH_CHILD"])
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    mode, S = cfg["mode"], int(cfg["shards"])
    n_docs = int(cfg["docs"])
    nq = int(cfg.get("queries", 96))
    batch = int(cfg.get("batch", 16))
    bdir = cfg.get("dir") or tempfile.mkdtemp(prefix="osse_mesh_")
    rep: dict = {"mode": mode, "shards": S, "docs": n_docs}

    if mode == "failover":
        # chaos leg: kill one mesh shard's serving twin mid-serving —
        # the next wave packs from the survivor (drain-before-refresh),
        # same answers, zero lost queries
        from open_source_search_engine_tpu.parallel.sharded import \
            MeshResident
        sc = _mesh_build_sc(bdir, S, n_docs, n_replicas=2)
        mr = MeshResident(sc)
        qs = _make_queries(8, seed=7)
        key = lambda res: [(r.docid, round(r.score, 3))
                           for r in res.results]
        lost = 0
        try:
            base = [mr.serve(q, topk=10, with_snippets=False)
                    for q in qs]
            sc.hostmap.mark_dead(0, 0)
            after = []
            for q in qs:
                try:
                    after.append(mr.serve(q, topk=10,
                                          with_snippets=False))
                except Exception:  # noqa: BLE001 — a lost query
                    lost += 1
            parity = (len(after) == len(base)
                      and all(key(a) == key(b) and not a.degraded
                              for a, b in zip(after, base)))
            rep.update({"lost": lost, "parity": parity,
                        "ok": lost == 0 and parity})
        finally:
            mr.stop()
        print(json.dumps(rep))
        return

    qs = _make_queries(nq + batch, seed=7)
    if mode == "ref":
        # the single-chip production path holding the SAME corpus the
        # gate's mesh point shards over — the strong-scaling baseline
        from open_source_search_engine_tpu.build import docproc
        from open_source_search_engine_tpu.index.collection import \
            Collection
        from open_source_search_engine_tpu.query import engine
        coll = Collection("bench", bdir)
        coll.conf.pqr_enabled = False
        if coll.num_docs < n_docs:
            docproc.index_batch(coll, list(_gen_docs(n_docs)))
            coll.posdb.dump()
            coll.titledb.dump()
            coll.save()
        run = lambda b: engine.search_device_batch(
            coll, b, topk=10, with_snippets=False)
    else:
        from open_source_search_engine_tpu.parallel.sharded import \
            MeshResident
        sc = _mesh_build_sc(bdir, S, n_docs)
        mr = MeshResident(sc)
        run = lambda b: mr.serve_batch(b, topk=10, with_snippets=False)

    run(qs[:batch])  # compile warm
    t0 = time.perf_counter()
    for a in range(batch, len(qs), batch):
        run(qs[a:a + batch])
    qps = (len(qs) - batch) / (time.perf_counter() - t0)
    rep.update({"qps": round(qps, 2), **_backend_record()})
    if mode == "mesh" and cfg.get("jit"):
        rep["jit"] = _mesh_jit_leg(mr)
    if mode == "mesh":
        mr.stop()
    print(json.dumps(rep))


def main_mesh() -> dict:
    """Mesh serving gate (BENCH_MESH=1): the scale curve of the
    mesh-RESIDENT serving path — qps vs shard count at FIXED docs per
    shard, each point a subprocess forcing that many host devices
    (``--xla_force_host_platform_device_count``), so the multi-chip
    program runs exactly as on a slice, minus the ICI.

    Gates (exit 1 on violation):
    * the in-jit merge at 4 shards sustains ≥ BENCH_MESH_MIN_SPEEDUP
      (default 1.5×) the qps of the single-chip production path
      holding the SAME corpus — the Msg3a-on-device headline;
    * jitwatch attributes ZERO compiles/retraces/off-boundary
      transfers to 64 steady-state mesh waves of varying (bucketed)
      batch sizes — only the wave-boundary device_put/device_get
      touch the host between shard intersection and merged top-k;
    * killing one mesh shard's serving twin mid-serving loses zero
      queries and degrades to the twin with identical answers.

    CPU-device numbers validate SCALING SHAPE and the host-hop
    deletion, not absolute TPU qps (the JSON says which backend
    measured them)."""
    import subprocess

    shards = [int(s) for s in os.environ.get(
        "BENCH_MESH_SHARDS", "1,2,4,8").split(",")]
    dps = int(os.environ.get("BENCH_MESH_DPS", "400"))
    nq = int(os.environ.get("BENCH_MESH_QUERIES", "96"))
    min_speedup = float(os.environ.get("BENCH_MESH_MIN_SPEEDUP", "1.5"))
    gate_s = 4 if 4 in shards else max(shards)
    bdir = os.environ.get("BENCH_DIR")

    def child(cfg: dict, devices: int) -> dict:
        if bdir:
            cfg["dir"] = os.path.join(
                bdir, f"{cfg['mode']}{cfg['shards']}x{cfg['docs']}")
        env = dict(os.environ)
        env["BENCH_MESH_CHILD"] = json.dumps(cfg)
        env["XLA_FLAGS"] = (f"{env.get('XLA_FLAGS', '')} "
                            f"--xla_force_host_platform_device_count="
                            f"{max(devices, 1)}")
        p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, capture_output=True, text=True,
                           timeout=3600)
        sys.stderr.write(p.stderr[-2000:])
        for line in reversed(p.stdout.strip().splitlines()):
            try:
                rec = json.loads(line)
                if rec.get("mode") == cfg["mode"]:
                    return rec
            except ValueError:
                continue
        return {"mode": cfg["mode"], "error":
                f"child rc={p.returncode}: {p.stdout[-300:]}"}

    curve = [child({"mode": "mesh", "shards": s, "docs": s * dps,
                    "queries": nq, "jit": s == gate_s}, devices=s)
             for s in shards]
    ref = child({"mode": "ref", "shards": 1, "docs": gate_s * dps,
                 "queries": nq}, devices=1)
    failover = child({"mode": "failover", "shards": 2,
                      "docs": int(os.environ.get(
                          "BENCH_MESH_FAILOVER_DOCS", "120"))},
                     devices=2)

    gate_pt = next((p for p in curve if p.get("shards") == gate_s), {})
    qps_mesh = gate_pt.get("qps") or 0.0
    qps_ref = ref.get("qps") or 0.0
    speedup = qps_mesh / qps_ref if qps_ref else 0.0
    jit = gate_pt.get("jit", {})
    gates = {
        f"speedup_{gate_s}_shards_ge_{min_speedup}x":
            speedup >= min_speedup,
        "jit_zero_compiles_retraces_offboundary":
            bool(jit.get("ok")),
        "failover_zero_lost_identical":
            bool(failover.get("ok")),
    }
    ok = all(gates.values())
    rep = {
        "metric": "mesh_serve_speedup_vs_single_chip",
        "value": round(speedup, 2), "unit": "x",
        "ok": ok, "gates": gates,
        "gate_shards": gate_s, "docs_per_shard": dps,
        "qps_mesh": qps_mesh, "qps_single_chip_same_corpus": qps_ref,
        "scale_curve": [{k: p.get(k) for k in
                         ("shards", "docs", "qps", "error")}
                        for p in curve],
        "jit": jit, "failover": failover,
        **_backend_record(),
    }
    print(json.dumps(rep))
    return rep


def main_transport() -> None:
    """Transport microbench (BENCH_TRANSPORT=1): the cluster RPC plane
    on an in-process loopback mini cluster. Reports pooled keep-alive
    vs dial-per-request throughput (the pre-pool urlopen baseline), and
    hedged-read tail latency against a deliberately wedged twin vs
    riding the wedge out. Loopback/CPU numbers — the point is the
    RELATIVE spread, not absolute RPC/s."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from open_source_search_engine_tpu.parallel import cluster as cl
    from open_source_search_engine_tpu.parallel import transport as tr

    bdir = tempfile.mkdtemp(prefix="osse_bench_transport_")
    n_rpc = int(os.environ.get("BENCH_TRANSPORT_RPCS", "400"))
    nodes = []
    for i in range(2):
        node = cl.ShardNodeServer(os.path.join(bdir, f"n{i}"))
        for d in range(30):
            node.handle("/rpc/index", {
                "url": f"http://bench.test/{i}-{d}",
                "content": (f"<html><body><p>bench words filler "
                            f"token{d}</p></body></html>")})
        node.start()
        nodes.append(node)
    addrs = [f"127.0.0.1:{n.port}" for n in nodes]

    def pct(lats, q):
        return lats[min(len(lats) - 1, int(len(lats) * q))]

    def run_pings(pooled: bool):
        lats = []
        t = tr.Transport()
        t0 = time.perf_counter()
        for k in range(n_rpc):
            if not pooled:
                t.close()  # drop the keep-alive socket: dial per call
            q0 = time.perf_counter()
            t.request(addrs[k % 2], "/rpc/ping", {}, timeout=5.0)
            lats.append(1000.0 * (time.perf_counter() - q0))
        dt = time.perf_counter() - t0
        t.close()
        lats.sort()
        return {"rpc_s": round(n_rpc / dt, 1),
                "p50_ms": round(pct(lats, 0.50), 3),
                "p99_ms": round(pct(lats, 0.99), 3)}

    pooled = run_pings(pooled=True)
    dialed = run_pings(pooled=False)

    # hedged read racing a wedged primary vs sending only to it
    wedge_s = 0.5
    real_handle = nodes[0].handle

    def wedged_handle(path, payload):
        if path == "/rpc/search":
            time.sleep(wedge_s)
        return real_handle(path, payload)

    nodes[0].handle = wedged_handle
    payload = {"q": "bench words", "topk": 5}
    hedge_lats, ride_lats = [], []
    for _ in range(16):
        # fresh transport per race: this bench PINS the wedged twin as
        # the primary, so a carried-over EWMA (fattened by the wedge)
        # would stretch the hedge leash — in the real client path the
        # hostmap demotes a penalized twin from primary instead
        t = tr.Transport()
        q0 = time.perf_counter()
        out, _, _ = t.hedged(addrs, "/rpc/search", payload, timeout=30.0)
        assert out and out.get("ok")
        hedge_lats.append(1000.0 * (time.perf_counter() - q0))
        t.close()
    t = tr.Transport()
    for _ in range(4):
        q0 = time.perf_counter()
        t.request(addrs[0], "/rpc/search", payload, timeout=30.0)
        ride_lats.append(1000.0 * (time.perf_counter() - q0))
    t.close()
    for n in nodes:
        n.stop()
    hedge_lats.sort()
    ride_lats.sort()
    print(json.dumps({
        **_backend_record(),
        "metric": "transport_rpc_per_sec_pooled",
        "value": pooled["rpc_s"], "unit": "rpc/s",
        "vs_baseline": round(pooled["rpc_s"] / max(dialed["rpc_s"], 1e-9),
                             2),
        "pooled": pooled,
        "dial_per_call": dialed,
        "wedged_twin_ms": {
            "wedge_ms": 1000.0 * wedge_s,
            "hedged_p50": round(pct(hedge_lats, 0.50), 1),
            "hedged_p99": round(pct(hedge_lats, 0.99), 1),
            "unhedged_p50": round(pct(ride_lats, 0.50), 1)},
    }))


def main_cache() -> None:
    """Cache-plane microbench (BENCH_CACHE=1): a fixed-seed Zipf query
    replay through a 2-node in-process ClusterClient, run twice — cache
    plane on vs off (``use_cache``/``enabled`` A/B levers). Reports the
    front-cache hit rate and the p50 of REPEATED queries (a query's
    second and later occurrences — the population a result cache
    exists for) cached vs uncached. Loopback/CPU numbers; the point is
    the relative spread."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import random

    from open_source_search_engine_tpu.cache import g_cacheplane
    from open_source_search_engine_tpu.parallel import cluster as cl

    bdir = tempfile.mkdtemp(prefix="osse_bench_cache_")
    n_docs = int(os.environ.get("BENCH_CACHE_DOCS", "40"))
    n_q = int(os.environ.get("BENCH_CACHE_QUERIES", "200"))
    vocab = ("alpha bravo charlie delta echo foxtrot golf hotel india "
             "juliet kilo lima mike november oscar papa quebec romeo "
             "sierra tango uniform victor whiskey yankee").split()
    nodes = []
    for i in range(2):
        node = cl.ShardNodeServer(os.path.join(bdir, f"n{i}"))
        for d in range(n_docs):
            words = " ".join(vocab[(d + j) % len(vocab)]
                             for j in range(6))
            node.handle("/rpc/index", {
                "url": f"http://bench.test/{i}-{d}",
                "content": (f"<html><body><p>{words} filler "
                            f"token{d}</p></body></html>")})
        node.start()
        nodes.append(node)
    conf = cl.HostsConf.parse(
        "num-mirrors: 0\n"
        + "\n".join(f"127.0.0.1:{n.port}" for n in nodes))

    # fixed-seed Zipf(s=1.1) mix over a small distinct-query set: a
    # few hot heads, a long-ish tail — the SERP traffic shape a result
    # cache lives on
    distinct = ([w for w in vocab[:12]]
                + [f"{vocab[i]} {vocab[(i * 7 + 3) % len(vocab)]}"
                   for i in range(12)])
    weights = [1.0 / (r + 1) ** 1.1 for r in range(len(distinct))]
    stream = random.Random(6).choices(distinct, weights=weights, k=n_q)

    def pct(lats, q):
        return lats[min(len(lats) - 1, int(len(lats) * q))]

    def replay(use_cache: bool) -> dict:
        g_cacheplane.flush()
        for n in nodes:
            n._search_cache.enabled = use_cache
        client = cl.ClusterClient(conf, use_heartbeat=False,
                                  use_cache=use_cache)
        seen: set = set()
        repeat_lats = []
        t0 = time.perf_counter()
        for q in stream:
            q0 = time.perf_counter()
            client.search(q, topk=10)
            dt = 1000.0 * (time.perf_counter() - q0)
            if q in seen:
                repeat_lats.append(dt)
            seen.add(q)
        wall = time.perf_counter() - t0
        st = client._result_cache.stats()
        client.close()
        repeat_lats.sort()
        return {"qps": round(n_q / wall, 1),
                "repeat_p50_ms": round(pct(repeat_lats, 0.50), 3),
                "repeat_p99_ms": round(pct(repeat_lats, 0.99), 3),
                "front_hit_rate": round(st["hit_rate"], 3)}

    # warmup absorbs JAX compiles so neither timed run pays them
    replay(use_cache=False)
    uncached = replay(use_cache=False)
    cached = replay(use_cache=True)
    for n in nodes:
        n.stop()
    speedup = round(uncached["repeat_p50_ms"]
                    / max(cached["repeat_p50_ms"], 1e-9), 2)
    print(json.dumps({
        **_backend_record(),
        "metric": "cache_hot_query_p50_speedup",
        "value": speedup, "unit": "x", "vs_baseline": speedup,
        "queries": n_q, "distinct": len(distinct),
        "cached": cached, "uncached": uncached,
    }))


def main_trace() -> None:
    """Tracing-plane microbench (BENCH_TRACE=1): the cost of leaving
    the tracer ON in production. A/B on the host (CPU) query path:
    tracing disabled (sample_n=0) vs enabled-but-unsampled (the 1-in-N
    steady state every non-kept query pays) — alternating best-of-N
    passes so clock drift hits both arms equally. The unsampled arm
    must stay within 2% of disabled, or this exits 1. Also reports the
    open/close cost of one SAMPLED span (the price a kept trace pays
    per stage)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from open_source_search_engine_tpu.build import docproc
    from open_source_search_engine_tpu.index.collection import Collection
    from open_source_search_engine_tpu.query import engine
    from open_source_search_engine_tpu.utils import trace as tm
    from open_source_search_engine_tpu.utils.trace import g_tracer

    bdir = tempfile.mkdtemp(prefix="osse_bench_trace_")
    coll = Collection("trbench", bdir)
    docproc.index_batch(coll, [
        (f"http://bench.test/t{d}",
         f"<html><body><p>trace bench words filler token{d % 37} "
         f"extra{d % 11}</p></body></html>")
        for d in range(240)])
    qs = [f"bench token{k % 37}" for k in range(48)]

    def one_pass(sample_n: int) -> float:
        g_tracer.configure(sample_n=sample_n, slow_ms=1e12)
        t0 = time.perf_counter()
        for q in qs:
            with g_tracer.start("bench.query", q=q):
                engine.search(coll, q, topk=10, with_snippets=False)
        return time.perf_counter() - t0

    one_pass(0)          # warm: compiles/caches out of the measurement
    one_pass(10 ** 9)
    passes = int(os.environ.get("BENCH_TRACE_PASSES", "7"))
    best_off = best_on = float("inf")
    for _ in range(passes):
        best_off = min(best_off, one_pass(0))
        best_on = min(best_on, one_pass(10 ** 9))
    overhead = (best_on - best_off) / best_off

    # sampled span cost: tight open/close loop under one kept trace
    n_spans = 50_000
    g_tracer.configure(sample_n=1)
    with g_tracer.start("bench.spans", sampled=True):
        t0 = time.perf_counter()
        for _ in range(n_spans):
            with tm.span("s"):
                pass
        span_s = time.perf_counter() - t0
    g_tracer.ring.clear()

    ok = overhead < 0.02
    print(json.dumps({
        **_backend_record(),
        "metric": "trace_unsampled_overhead_pct",
        "value": round(100.0 * overhead, 3), "unit": "%",
        "ok": ok, "budget_pct": 2.0,
        "best_off_s": round(best_off, 4),
        "best_unsampled_s": round(best_on, 4),
        "queries_per_pass": len(qs),
        "ns_per_span_sampled": round(1e9 * span_s / n_spans, 1),
    }))
    if not ok:
        sys.exit(1)


def main_dispatch() -> None:
    """Resident-loop microbench (BENCH_DISPATCH=1): steady-state
    enqueue-to-result latency through the double-buffered serving loop
    (query/resident.py) plus the packed-layout HBM model. Two numbers,
    one budget:

    * p50/p99 of ticket enqueue→resolve with the pipeline kept at
      depth 2 (the next wave is enqueued before the previous resolves
      — the dispatch-RTT-floor attack this loop exists for);
    * modelled HBM bytes/query for the live packed layout (f16
      impacts, uint8 doc meta, length-bucketed Lsp tiles) vs the
      legacy unpacked layout — the SURVEY §7 stage-8 win. The packed/
      legacy ratio must be ≤ 0.7 or this exits 1.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from collections import deque

    from open_source_search_engine_tpu.build import docproc
    from open_source_search_engine_tpu.index.collection import Collection
    from open_source_search_engine_tpu.query import engine
    from open_source_search_engine_tpu.query.engine import (
        get_device_index, get_resident_loop)

    bdir = tempfile.mkdtemp(prefix="osse_bench_disp_")
    coll = Collection("dispbench", bdir)
    docproc.index_batch(coll, [
        (f"http://bench.test/d{d}",
         f"<html><body><p>dispatch bench words filler token{d % 37} "
         f"extra{d % 11} rare{d % 101}</p></body></html>")
        for d in range(int(os.environ.get("BENCH_DISPATCH_DOCS",
                                          "240")))])
    di = get_device_index(coll)
    # zipf-ish mix: head terms (every doc), mid (1/37), tail (1/101) —
    # unique strings so no cache can fake the latency (module honesty
    # note)
    n_q = int(os.environ.get("BENCH_DISPATCH_QUERIES", "192"))
    qs = [f"bench token{k % 37}" if k % 3 else f"words rare{k % 101}"
          for k in range(n_q)]
    plans = [engine._compile_cached(q, 0) for q in qs]

    loop = get_resident_loop(coll)
    # warm the shape buckets + the loop itself out of the measurement
    for p in plans[:8]:
        loop.submit([p], topk=64).wait(timeout=120)

    lats: list[float] = []
    inflight: deque = deque()
    t_all = time.perf_counter()
    for p in plans:
        inflight.append((loop.submit([p], topk=64),
                         time.perf_counter()))
        while len(inflight) >= 2:  # keep depth-2 steady state
            tk, t0 = inflight.popleft()
            tk.wait(timeout=120)
            lats.append(time.perf_counter() - t0)
    while inflight:
        tk, t0 = inflight.popleft()
        tk.wait(timeout=120)
        lats.append(time.perf_counter() - t0)
    wall = time.perf_counter() - t_all

    lats.sort()
    p50 = 1000 * lats[len(lats) // 2]
    p99 = 1000 * lats[min(len(lats) - 1, int(len(lats) * 0.99))]

    dplans = [di.plan(p) for p in plans]
    packed_b = di.wave_bytes_per_query(dplans, packed=True)
    legacy_b = di.wave_bytes_per_query(dplans, packed=False)
    ratio = packed_b / legacy_b

    ok = ratio <= 0.7
    print(json.dumps({
        **_backend_record(),
        "metric": "dispatch_enqueue_to_result_p50_ms",
        "value": round(p50, 2), "unit": "ms",
        "p99_ms": round(p99, 2),
        "queries": len(lats), "qps": round(len(lats) / wall, 1),
        "waves": loop.waves_issued,
        "hbm_bytes_per_query_packed": round(packed_b),
        "hbm_bytes_per_query_legacy": round(legacy_b),
        "packed_ratio": round(ratio, 3),
        "ok": ok, "budget_ratio": 0.7,
    }))
    if not ok:
        sys.exit(1)


def main_jit() -> None:
    """Trace-discipline gate (BENCH_JIT=1): 64 steady-state resident
    waves after warmup, under the jit watcher. The PR 6 headline —
    steady-state dispatch is one async enqueue — is only true while
    nothing recompiles and nothing syncs to host off the boundary, so
    this exits 1 if the watcher attributes ANY compile, retrace, or
    off-boundary transfer to the measured waves (the one blessed
    ``device_get`` per wave in devindex.collect_batch is on-boundary
    and allowed). The attribution table goes into the bench JSON so a
    breach names its call site.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from collections import deque

    from open_source_search_engine_tpu.build import docproc
    from open_source_search_engine_tpu.index.collection import Collection
    from open_source_search_engine_tpu.query import engine
    from open_source_search_engine_tpu.query.engine import (
        get_device_index, get_resident_loop)
    from open_source_search_engine_tpu.utils import jitwatch

    bdir = tempfile.mkdtemp(prefix="osse_bench_jit_")
    coll = Collection("jitbench", bdir)
    docproc.index_batch(coll, [
        (f"http://bench.test/d{d}",
         f"<html><body><p>dispatch bench words filler token{d % 37} "
         f"extra{d % 11} rare{d % 101}</p></body></html>")
        for d in range(int(os.environ.get("BENCH_JIT_DOCS", "240")))])
    get_device_index(coll)
    # same zipf-ish mix as BENCH_DISPATCH: head/mid/tail terms, varied
    # term counts so several shape buckets are live
    qs = [f"bench token{k % 37}" if k % 3 else f"words rare{k % 101}"
          for k in range(24)]
    qs += [f"filler extra{k % 11} token{k % 37}" for k in range(8)]
    plans = [engine._compile_cached(q, 0) for q in qs]

    jitwatch.enable()
    loop = get_resident_loop(coll)
    # warmup: every plan once — compiles every live shape bucket, and
    # is excluded from the gate
    for p in plans:
        loop.submit([p], topk=64).wait(timeout=120)

    jitwatch.reset()
    n_waves = int(os.environ.get("BENCH_JIT_WAVES", "64"))
    lats: list[float] = []
    inflight: deque = deque()
    for k in range(n_waves):
        inflight.append((loop.submit([plans[k % len(plans)]], topk=64),
                         time.perf_counter()))
        while len(inflight) >= 2:  # depth-2 steady state
            tk, t0 = inflight.popleft()
            tk.wait(timeout=120)
            lats.append(time.perf_counter() - t0)
    while inflight:
        tk, t0 = inflight.popleft()
        tk.wait(timeout=120)
        lats.append(time.perf_counter() - t0)

    snap = jitwatch.snapshot()
    t = snap["totals"]
    offb = [e for e in snap["events"]
            if e["kind"] == "transfer" and not e["boundary"]]
    ok = (t["compiles"] == 0 and t["retraces"] == 0
          and t["transfers_offboundary"] == 0)
    lats.sort()

    # the same discipline for the MESH program: a subprocess (it must
    # force 4 host devices before jax imports) runs 64 varying-batch
    # steady-state mesh waves under the watcher — transfers only at
    # the wave's issue/collect boundary
    mesh_jit: dict = {}
    if os.environ.get("BENCH_JIT_MESH", "1") != "0":
        import subprocess
        cfg = {"mode": "mesh", "shards": 4,
               "docs": 4 * int(os.environ.get("BENCH_JIT_MESH_DPS",
                                              "60")),
               "queries": 16, "jit": True}
        env = dict(os.environ)
        env["BENCH_MESH_CHILD"] = json.dumps(cfg)
        env["XLA_FLAGS"] = (f"{env.get('XLA_FLAGS', '')} "
                            "--xla_force_host_platform_device_count=4")
        p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, capture_output=True, text=True,
                           timeout=1800)
        for line in reversed(p.stdout.strip().splitlines()):
            try:
                rec = json.loads(line)
                if rec.get("mode") == "mesh":
                    mesh_jit = rec.get("jit", {})
                    break
            except ValueError:
                continue
        if not mesh_jit:
            mesh_jit = {"ok": False, "error":
                        f"mesh child rc={p.returncode}: "
                        f"{p.stdout[-300:]}"}
        ok = ok and bool(mesh_jit.get("ok"))

    print(json.dumps({
        **_backend_record(),
        "metric": "jit_steady_state_compiles",
        "value": t["compiles"], "unit": "compiles",
        "waves": n_waves,
        "p50_ms": round(1000 * lats[len(lats) // 2], 2),
        "retraces": t["retraces"],
        "transfers": t["transfers"],
        "transfers_offboundary": t["transfers_offboundary"],
        "offboundary_sites": [e["site"] for e in offb],
        "attribution": snap["events"],
        "mesh": mesh_jit,
        "ok": ok,
        "budget": "zero compiles/retraces/off-boundary transfers "
                  "(flat resident waves AND mesh waves)",
    }))
    if not ok:
        sys.exit(1)


def main_devobs() -> dict:
    """Device-telemetry gate (BENCH_DEVOBS=1): the devwatch plane must
    be free (<2% steady-state overhead), honest (HBM ledger agrees
    with the index's own accounting, and with ``memory_stats()`` on a
    real backend), and complete (a roofline entry for every dispatched
    shape bucket, the doctor stamp on the JSON line). Median
    per-ticket latency is compared devwatch-off vs devwatch-on; the
    one-time ``cost_analysis()`` per bucket is paid in an untimed
    populate pass, so the gate measures the steady-state fast path.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from open_source_search_engine_tpu.build import docproc
    from open_source_search_engine_tpu.index.collection import Collection
    from open_source_search_engine_tpu.query import engine
    from open_source_search_engine_tpu.query.engine import (
        get_device_index, get_resident_loop)
    from open_source_search_engine_tpu.utils import devwatch

    devwatch.disable()
    devwatch.reset()
    n_docs = int(os.environ.get("BENCH_DEVOBS_DOCS", "160"))
    n_waves = int(os.environ.get("BENCH_DEVOBS_WAVES", "40"))
    tol = float(os.environ.get("BENCH_DEVOBS_TOL", "0.02"))

    bdir = tempfile.mkdtemp(prefix="osse_bench_devobs_")
    coll = Collection("devobs", bdir)
    docproc.index_batch(coll, [
        (f"http://devobs.test/d{d}",
         f"<html><body><p>telemetry bench words token{d % 23} "
         f"extra{d % 7} rare{d % 61}</p></body></html>")
        for d in range(n_docs)])
    di = get_device_index(coll)
    qs = [f"bench token{k % 23}" if k % 3 else f"words rare{k % 61}"
          for k in range(12)]
    qs += [f"telemetry extra{k % 7} token{k % 23}" for k in range(6)]
    plans = [engine._compile_cached(q, 0) for q in qs]
    loop = get_resident_loop(coll)

    for p in plans:  # warm every shape bucket, devwatch off
        loop.submit([p], topk=32).wait(timeout=120)

    devwatch.enable()
    # one extra doc + a refresh through the production path populates
    # the ledger; one untimed pass per plan pays the one-time
    # cost_analysis() per bucket
    docproc.index_batch(coll, [("http://devobs.test/extra",
                                "<html><body><p>telemetry bench words "
                                "token1 extra1</p></body></html>")])
    for p in plans:
        loop.submit([p], topk=32).wait(timeout=120)

    # interleave off/on waves so host-timing drift (frequency scaling,
    # GC, page-cache warming) lands equally on both sides — a
    # sequential A-then-B layout folds the drift into the overhead
    off: list = []
    on: list = []
    for k in range(2 * n_waves):
        if k % 2:
            devwatch.enable()
        else:
            devwatch.disable()
        t0 = time.perf_counter()
        loop.submit([plans[k % len(plans)]], topk=32).wait(timeout=120)
        (on if k % 2 else off).append(time.perf_counter() - t0)
    devwatch.enable()
    off.sort()
    on.sort()
    median_off = off[len(off) // 2]
    median_on = on[len(on) // 2]
    overhead = median_on / median_off - 1 if median_off > 0 else 0.0

    snap = devwatch.snapshot()
    ledger_bytes = devwatch.collection_bytes(coll.name)
    resident = int(di.resident_bytes())
    ledger_ok = ledger_bytes == resident

    # memory_stats gate: only binding where the backend reports it
    recon = snap.get("reconcile") or {}
    mem_ok, mem_checked = True, False
    for drec in (recon.get("devices") or []):
        in_use = drec.get("bytes_in_use")
        if in_use:
            mem_checked = True
            delta = abs(in_use - snap["total_bytes"])
            mem_ok = mem_ok and delta / in_use <= 0.05

    roofs = snap.get("rooflines") or []
    roof_ok = bool(roofs) and all(
        r.get("dispatches", 0) >= 1 and r.get("flops") is not None
        and r.get("bytes") is not None for r in roofs)

    br = _backend_record()
    stamp_ok = all(k in br for k in
                   ("doctor", "jax_version", "device_kind",
                    "device_count", "memory_stats"))

    ok = (overhead < tol and ledger_ok and mem_ok and roof_ok
          and stamp_ok)
    rep = {
        **br,
        "metric": "devwatch_overhead",
        "value": round(overhead * 100, 3), "unit": "percent",
        "waves": n_waves,
        "p50_off_ms": round(1000 * median_off, 3),
        "p50_on_ms": round(1000 * median_on, 3),
        "ledger_bytes": ledger_bytes,
        "resident_bytes": resident,
        "ledger_ok": ledger_ok,
        "memory_stats_checked": mem_checked,
        "memory_stats_ok": mem_ok,
        "rooflines": len(roofs),
        "roofline_ok": roof_ok,
        "stamp_ok": stamp_ok,
        "wave_records": len(snap.get("waves") or []),
        "ok": ok,
        "budget": f"devwatch-on overhead < {tol:.0%}; ledger == "
                  "resident_bytes; memory_stats within 5% where "
                  "reported; roofline per dispatched bucket; doctor "
                  "stamp present",
    }
    print(json.dumps(rep))
    devwatch.disable()
    devwatch.reset()
    shutil.rmtree(bdir, ignore_errors=True)
    return rep


def _build_cols_mismatch(host, dev) -> list:
    """Names of device-index columns that differ bitwise from the host
    oracle's (empty == bit-exact)."""
    import numpy as np
    bad = []
    for name in ("dir_termids", "base_df", "dir_dstart", "dir_pstart",
                 "base_docids", "h_doc_col", "d_payload", "d_docc",
                 "d_doc", "d_rs", "d_cnt", "d_siterank", "d_doclang",
                 "d_cube", "d_dense_rs", "d_dense_cnt"):
        a = np.asarray(getattr(host, name))
        b = np.asarray(getattr(dev, name))
        if a.shape != b.shape or not np.array_equal(a, b):
            bad.append(name)
    for name in ("d_imp", "d_dense_imp"):
        a = np.asarray(getattr(host, name)).view(np.uint16)
        b = np.asarray(getattr(dev, name)).view(np.uint16)
        if a.shape != b.shape or not np.array_equal(a, b):
            bad.append(name)
    return bad


def main_build() -> dict:
    """Ingest-plane gate (BENCH_BUILD=1): the device posting
    sort/dedup/pack pipeline (``build/devbuild.py``) measured end to
    end. Three legs, all must hold:

    1. parity — a seeded multi-run corpus (tombstones, re-adds) built
       by the device plane must be BITWISE equal to the host oracle:
       every base column, directory table and f16 impact;
    2. throughput — index BENCH_BUILD_DOCS docs through the real
       tokenize/pack pipeline, then time a cold full device base
       rebuild; the rebuild must land under BENCH_BUILD_REBUILD_S
       (default 60 s — r04 measured ~450 s of host build at 100k docs)
       and the measured docs/s is the emitted metric;
    3. jit discipline — repeated same-bucket delta folds under
       jitwatch: zero compiles/retraces once the bucket is warm.

    Prints ONE JSON line stamped by ``_backend_record()``; returns the
    report."""
    from open_source_search_engine_tpu.build import docproc
    from open_source_search_engine_tpu.index.collection import Collection
    from open_source_search_engine_tpu.query.devindex import DeviceIndex
    from open_source_search_engine_tpu.utils import jitwatch
    from open_source_search_engine_tpu.utils.stats import g_stats

    def _ctr(name: str) -> int:
        return g_stats.counters.get(name, 0)

    # --- leg 1: bitwise parity vs the host oracle -------------------
    p_docs = int(os.environ.get("BENCH_BUILD_PARITY_DOCS", "300"))
    pdir = tempfile.mkdtemp(prefix="osse_bench_build_par_")
    pc = Collection("par", pdir)
    pd = list(_gen_docs(p_docs))
    docproc.index_batch(pc, pd[:p_docs // 2])
    pc.posdb.dump()
    pc.titledb.dump()
    docproc.index_batch(pc, pd[p_docs // 2:])
    pc.posdb.dump()
    # run 3: tombstones + a re-add so annihilation crosses run bounds
    docproc.remove_document(pc, pd[1][0])
    docproc.index_document(pc, *pd[2])
    pc.posdb.dump()
    fb0 = _ctr("build.devbuild_fallback")
    # device first — the device plane never writes the disk cache, so
    # the host oracle build below derives from scratch
    os.environ["OSSE_DEVBUILD"] = "1"
    dev = DeviceIndex(pc)
    os.environ["OSSE_DEVBUILD"] = "0"
    host = DeviceIndex(pc)
    os.environ["OSSE_DEVBUILD"] = "1"
    mismatch = _build_cols_mismatch(host, dev)
    parity_ok = not mismatch and _ctr("build.devbuild_fallback") == fb0
    shutil.rmtree(pdir, ignore_errors=True)

    # --- leg 2: measured ingest + cold device rebuild ---------------
    n_docs = int(os.environ.get("BENCH_BUILD_DOCS", str(N_DOCS)))
    bound_s = float(os.environ.get("BENCH_BUILD_REBUILD_S", "60"))
    bdir = os.environ.get("BENCH_DIR") or tempfile.mkdtemp(
        prefix="osse_bench_build_")
    coll = Collection("bench", bdir)
    t0 = time.perf_counter()
    built = coll.num_docs < n_docs
    if built:
        chunk: list = []
        done = 0
        for url, html in _gen_docs(n_docs):
            chunk.append((url, html))
            if len(chunk) >= 512:
                docproc.index_batch(coll, chunk)
                done += len(chunk)
                chunk = []
                if done % 20480 == 0:
                    print(f"# indexed {done}/{n_docs} "
                          f"({done / (time.perf_counter() - t0):.0f} "
                          "docs/s)", file=sys.stderr)
        if chunk:
            docproc.index_batch(coll, chunk)
        coll.posdb.dump()
        coll.titledb.dump()
        coll.save()
    index_s = time.perf_counter() - t0
    # a cold rebuild: the host pipeline's disk cache would short-circuit
    # _build_base entirely and time a np.load instead of the plane
    shutil.rmtree(coll.posdb.dir / "devcache", ignore_errors=True)
    db0 = _ctr("build.device_base")
    fb1 = _ctr("build.devbuild_fallback")
    t0 = time.perf_counter()
    idx = DeviceIndex(coll)
    rebuild_s = time.perf_counter() - t0
    device_ran = _ctr("build.device_base") == db0 + 1 \
        and _ctr("build.devbuild_fallback") == fb1
    rebuild_ok = device_ran and rebuild_s < bound_s

    # --- leg 3: same-bucket delta folds stay compile-free -----------
    waves = int(os.environ.get("BENCH_BUILD_WAVES", "6"))
    per_wave = int(os.environ.get("BENCH_BUILD_WAVE_DOCS", "16"))

    def _wave(w: int) -> list:
        # tiny fixed-shape docs: every fold lands in the same padded
        # shape bucket, so steady state must not compile or retrace
        return [(f"http://fold{w}.bench.test/d{i}",
                 f"<html><body><p>fold words batch{w % 3} tok{i % 7} "
                 "steady bucket probe</p></body></html>")
                for i in range(per_wave)]

    jitwatch.enable()
    docproc.index_batch(coll, _wave(0))   # warm: compiles the bucket
    idx.refresh()
    jitwatch.reset()
    for w in range(1, waves + 1):
        docproc.index_batch(coll, _wave(w))
        idx.refresh()
    snap = jitwatch.snapshot()
    t = snap["totals"]
    jit_ok = t["compiles"] == 0 and t["retraces"] == 0

    ok = parity_ok and rebuild_ok and jit_ok
    rebuild_dps = n_docs / rebuild_s if rebuild_s > 0 else 0.0
    rep = {
        "metric": "build_docs_per_sec",
        "value": round(rebuild_dps, 1),
        "unit": "docs/s",
        "docs": n_docs,
        "index_s": round(index_s, 2),
        "index_docs_per_s": round(n_docs / index_s, 1)
        if built and index_s > 0 else None,
        "rebuild_s": round(rebuild_s, 2),
        "rebuild_bound_s": bound_s,
        "device_ran": device_ran,
        "parity": {"docs": p_docs, "ok": parity_ok,
                   "mismatch": mismatch},
        "jit": {"waves": waves, "wave_docs": per_wave,
                "compiles": t["compiles"], "retraces": t["retraces"],
                "ok": jit_ok},
        "ok": ok,
        **_backend_record(),
        "budget": f"bit-exact parity + cold rebuild < {bound_s:.0f}s "
                  "+ zero steady-state compiles/retraces",
    }
    print(json.dumps(rep))
    return rep


def main() -> None:
    try:
        jax = _init_backend()
    except Exception as e:  # noqa: BLE001
        _emit_stale_curve(f"backend init failed after retries: {e}")
        return

    # persistent XLA compile cache: warmup cost amortizes across runs
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/osse_xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:
        pass

    from open_source_search_engine_tpu.build import docproc
    from open_source_search_engine_tpu.index.collection import Collection
    from open_source_search_engine_tpu.query import engine

    # BENCH_DIR reuses a corpus dir across runs (indexing 100k docs is
    # ~5 min; iterating on query-path changes shouldn't pay it again)
    bdir = os.environ.get("BENCH_DIR") or tempfile.mkdtemp(
        prefix="osse_bench_")
    coll = Collection("bench", bdir)
    t0 = time.perf_counter()
    built = coll.num_docs < N_DOCS  # corpus build actually runs
    if built:
        chunk: list = []
        done = 0
        for url, html in _gen_docs(N_DOCS):
            chunk.append((url, html))
            if len(chunk) >= 512:
                docproc.index_batch(coll, chunk)
                done += len(chunk)
                chunk = []
                if done % 20480 == 0:
                    print(f"# indexed {done}/{N_DOCS} "
                          f"({done / (time.perf_counter() - t0):.0f} "
                          "docs/s)", file=sys.stderr)
        if chunk:
            docproc.index_batch(coll, chunk)
        # dump → the measured path serves from the on-disk base (dense +
        # cube rows built); the remaining delta stays empty
        coll.posdb.dump()
        coll.titledb.dump()
        coll.save()
    build_s = time.perf_counter() - t0



    t0 = time.perf_counter()
    di = engine.get_device_index(coll)
    try:
        # BENCH_NO_WARM=1 skips the precompile sweep — the recovery
        # lever when a remote-compile RPC wedges mid-warm (observed on
        # the tunneled backend): rerun relying on the persistent cache
        # from the wedged attempt, eating any stragglers measured.
        if os.environ.get("BENCH_NO_WARM") != "1":
            di.warm()  # precompile every pinned kernel shape variant
    except Exception as e:  # noqa: BLE001 — tunnel hiccups happen
        # a transient backend error mid-warm must not kill the run:
        # unwarmed shapes just compile on first use (slower, measured)
        print(f"# warm() aborted ({e}); continuing unwarmed",
              file=sys.stderr)
    device_build_s = time.perf_counter() - t0

    # raw dispatch+fetch round trip: the floor under ANY single-query
    # latency on this backend (tunneled TPU ≈ 100 ms; the p50 below
    # should be read against it)
    import jax.numpy as jnp
    tiny = jax.jit(lambda x: x + 1)
    jax.device_get(tiny(jnp.zeros(8)))
    rtts = []
    for _ in range(5):
        t1 = time.perf_counter()
        jax.device_get(tiny(jnp.zeros(8)))
        rtts.append(time.perf_counter() - t1)
    rtt_ms = 1000 * sorted(rtts)[len(rtts) // 2]

    # with a reused corpus dir, salt the query seeds per run — the
    # tunneled backend may cache identical dispatches across processes,
    # which would fake the throughput of a repeated measurement
    salt = os.getpid() if os.environ.get("BENCH_DIR") else 0
    warm_qs = _make_queries(8 * BATCH + N_LAT + 8, seed=99 + salt)
    lat_qs = _make_queries(N_LAT, seed=1234 + salt)
    # (different seeds overlap rarely; uniqueness within each set is
    # what defeats the dispatch cache — warm queries are never measured)

    t0 = time.perf_counter()
    for i in range(0, 8 * BATCH, BATCH):  # warm batch buckets (B=32)
        engine.search_device_batch(coll, warm_qs[i:i + BATCH], topk=10,
                                   with_snippets=False)
    for q in warm_qs[8 * BATCH:]:          # warm single buckets (B=4)
        engine.search_device(coll, q, topk=10, with_snippets=False)
    warm_s = time.perf_counter() - t0

    # replay size: BASELINE.json's metric is a 10k-query replay; a
    # pilot pass estimates qps so the replay targets ~90 s of measured
    # wall (N_QUERIES env pins it instead when set). Every query is
    # unique, zipf-term, drawn from the same generator family — the
    # 10k log sampled down, not a different workload.
    pilot_qs = _make_queries(2 * BATCH, seed=31 + salt)
    t0 = time.perf_counter()
    for i in range(0, len(pilot_qs), BATCH):
        engine.search_device_batch(coll, pilot_qs[i:i + BATCH],
                                   topk=10, with_snippets=False)
    pilot_qps = len(pilot_qs) / (time.perf_counter() - t0)
    if os.environ.get("BENCH_QUERIES"):
        replay_n = N_QUERIES
    else:
        replay_n = max(512, min(10000,
                                BATCH * int(90 * pilot_qps / BATCH)))
    meas_qs = _make_queries(replay_n, seed=7 + salt)

    # --- measured: batched throughput over unique queries ---
    from open_source_search_engine_tpu.utils.stats import g_stats
    g_stats.reset()  # timers cover ONLY the measured pass
    esc0 = di.escalations
    # two batches in flight: batch N's host post-processing (titledb
    # fetches, clustering, PQR) overlaps batch N+1's device waves —
    # device_get releases the GIL, so one extra thread suffices. The
    # serving path's QueryBatcher runs the same two-deep overlap.
    from concurrent.futures import ThreadPoolExecutor
    t0 = time.perf_counter()
    with ThreadPoolExecutor(2) as ex:
        futs = [ex.submit(engine.search_device_batch, coll,
                          meas_qs[i:i + BATCH], topk=10,
                          with_snippets=False)
                for i in range(0, len(meas_qs), BATCH)]
        for f in futs:
            f.result()
    elapsed = time.perf_counter() - t0
    qps = len(meas_qs) / elapsed
    # snapshot NOW: the stage breakdown must cover ONLY the batched
    # throughput pass (the latency + recall passes below would bleed
    # host-path timers into it)
    snap = g_stats.snapshot()

    # --- measured: single-query latency distribution ---
    # one unmeasured same-distribution pass first: a single straggler
    # compile would otherwise own the p99 (distinct query strings so
    # the backend dispatch cache can't serve the measured pass)
    for q in _make_queries(N_LAT, seed=777 + salt):
        engine.search_device(coll, q, topk=10, with_snippets=False)
    lats = []
    for q in lat_qs:
        t1 = time.perf_counter()
        engine.search_device(coll, q, topk=10, with_snippets=False)
        lats.append(1000 * (time.perf_counter() - t1))
    lats.sort()
    p50 = lats[len(lats) // 2]
    p99 = lats[min(int(len(lats) * 0.99), len(lats) - 1)]

    # --- recall@10 vs the host flat path (the BASELINE.json contract:
    # qps at FIXED recall, not qps alone). Relevance is HOST-derived
    # only: the host page is fetched 200 deep and the relevant set is
    # every host docid scoring ≥ its 10th-best score (tie members
    # beyond rank 10 are interchangeable with it). recall = |device
    # top-10 ∩ relevant| / min(10, #host matches). Expected 1.0 — the
    # device kernels are bit-parity with the host scorer.
    recall_n = int(os.environ.get("BENCH_RECALL_QUERIES", "32"))
    recall_qs = meas_qs[:recall_n]
    rec_sum, rec_cnt = 0.0, 0
    # PQR's per-domain demotion is rank-dependent (0.85^k within one
    # registrable domain), so it stamps different scores onto docs
    # that tie in base score — recall must compare the UNDEMOTED
    # ranking or tie reordering reads as loss
    pqr_was = coll.conf.pqr_enabled
    coll.conf.pqr_enabled = False
    # wall budget: the host flat path is O(postings) per common-term
    # query — at 250k+ docs a full 32-query pass runs tens of minutes.
    # recall is a parity check, not a throughput number: however many
    # queries fit the budget are reported (count rides the JSON line)
    recall_deadline = time.perf_counter() + float(
        os.environ.get("BENCH_RECALL_BUDGET_S", "300"))
    for q in recall_qs:
        if time.perf_counter() > recall_deadline:
            break
        dev = engine.search_device(coll, q, topk=10,
                                   with_snippets=False,
                                   site_cluster=False)
        host = engine.search(coll, q, topk=200, with_snippets=False,
                             site_cluster=False)
        if not host.results:
            continue
        floor = host.results[min(9, len(host.results) - 1)].score \
            * (1 - 1e-6)
        relevant = {r.docid for r in host.results
                    if r.score >= floor}
        denom = min(10, host.total_matches)
        got = min(sum(1 for r in dev.results[:10]
                      if r.docid in relevant), denom)
        rec_sum += got / max(denom, 1)
        rec_cnt += 1
    coll.conf.pqr_enabled = pqr_was
    recall10 = round(rec_sum / max(rec_cnt, 1), 4)

    # --- qps-vs-docs scale curve: this machine's cache of measured
    # runs (one entry per corpus size, latest wins) — the flatness
    # claim vs the reference's "halves as index doubles"
    # (html/faq.html:320) needs the curve, not one point
    scale_path = os.path.expanduser("~/.cache/osse_bench_scale.json")
    scale = _load_scale()
    try:
        import subprocess
        commit = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5).stdout.strip()
    except Exception:
        commit = ""
    scale[str(N_DOCS)] = {
        "qps": round(qps, 2), "p50_ms": round(p50, 1),
        "p99_ms": round(p99, 1), "recall_at_10": recall10,
        "recall_queries": rec_cnt,
        "replay_n": len(meas_qs), "commit": commit,
        "ts": int(time.time())}
    try:
        os.makedirs(os.path.dirname(scale_path), exist_ok=True)
        with open(scale_path, "w") as f:
            json.dump(scale, f)
    except Exception:
        pass
    curve = _curve_of(scale)

    print(json.dumps({
        "metric": "queries_per_sec",
        "value": round(qps, 2),
        "unit": "qps",
        "vs_baseline": round(qps / BASELINE_QPS, 2),
        "p50_ms": round(p50, 1),
        "p99_ms": round(p99, 1),
        "recall_at_10": recall10,
        "recall_queries": rec_cnt,
        "replay_n": len(meas_qs),
        "docs": N_DOCS,
        "scale": curve,
        **_backend_record(),
    }))
    # --- stage breakdown (always on): where the measured time went
    # (snap taken right after the throughput pass) ---
    for k, v in sorted(snap.get("latencies", {}).items()):
        print(f"# {k}: n={v['count']} avg={v['avg_ms']:.1f} "
              f"min={v['min_ms']:.1f} max={v['max_ms']:.1f}",
              file=sys.stderr)
    import numpy as np
    # --- bandwidth roofline: HBM bytes the resident arrays span vs
    # what the measured pass could have streamed at v5e peak (819 GB/s)
    # — a ratio ≪ 1 means the pass is latency/RTT-bound, not BW-bound
    res_bytes = sum(
        int(np.prod(a.shape)) * a.dtype.itemsize
        for a in (di.d_payload, di.d_doc, di.d_imp, di.d_rs, di.d_cnt,
                  di.d_dense_imp, di.d_dense_rs, di.d_dense_cnt,
                  di.d_cube))
    n_waves = sum(v["count"] for k, v in snap.get(
        "latencies", {}).items() if k.startswith("devindex.wave"))
    print(f"# resident index: {res_bytes / 1e9:.2f} GB in HBM; "
          f"{n_waves} device waves in {elapsed:.2f}s measured; "
          f"one full-index sweep per wave would need "
          f"{res_bytes * n_waves / 819e9:.2f}s at v5e peak "
          "(819 GB/s)", file=sys.stderr)
    print(f"# dispatch+fetch RTT (median): {rtt_ms:.1f} ms — the "
          "floor under single-query p50 on this tunneled backend",
          file=sys.stderr)
    build_note = (f"{build_s:.0f}s build, "
                  f"{N_DOCS / max(build_s, 1e-9):.0f} docs/s"
                  if built else "reused BENCH_DIR corpus")
    print(f"# corpus={N_DOCS} docs ({build_note}; device build "
          f"{device_build_s:.1f}s), warmup {warm_s:.0f}s, "
          f"{len(meas_qs)} unique queries (batch={BATCH}) in "
          f"{elapsed:.2f}s, p50 {p50:.1f}ms p90 "
          f"{lats[int(len(lats) * 0.9)]:.1f}ms, "
          f"escalations {di.escalations - esc0}", file=sys.stderr)


def main_soak() -> dict:
    """Chaos soak gate (BENCH_SOAK=1): crawl → index → serve end to end
    on an in-process 2-shard × 2-twin cluster, with the chaos plane
    injecting the ancestral faults mid-flight. The scenario:

    1. a SpiderLoop crawls a synthetic linked web through the real
       fetch→parse→index pipeline, teeing every page into the cluster;
    2. an open-loop fixed-seed Zipf query load runs while chaos
       delays/refuses one backup twin's legs, kills a primary node
       mid-query (the hedge — not an error retry — must eat it), and a
       slice of the queries carry already-tight deadlines (the
       abandon/degrade path, never the lost path);
    3. the killed node restarts and heartbeats must revive it;
    4. a byte of one node's on-disk posting run is flipped; scrub must
       quarantine the run before any query can read it;
    5. a forced DailyMerge sweep runs under forced memory pressure,
       and the crawl-side grid is rebalanced 1 → 2 shards.

    The driver exits 1 unless EVERY gate holds: zero lost queries,
    hedge fired and won, corruption quarantined (detected — never
    served), deadline.abandoned > 0, a merge ran under pressure, the
    rebalance conserved docs, the twin recovered, p99 under
    BENCH_SOAK_P99_MS. Prints ONE JSON line; returns the report."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import random
    from datetime import datetime

    from open_source_search_engine_tpu.control.dailymerge import DailyMerge
    from open_source_search_engine_tpu.control.rebalance import rebalance
    from open_source_search_engine_tpu.parallel import cluster as cl
    from open_source_search_engine_tpu.parallel.sharded import (
        ShardedCollection)
    from open_source_search_engine_tpu.spider.fetcher import FetchResult
    from open_source_search_engine_tpu.spider.loop import SpiderLoop
    from open_source_search_engine_tpu.spider.scheduler import (
        SpiderScheduler, UrlFilterRule)
    from open_source_search_engine_tpu.utils import deadline as dlmod
    from open_source_search_engine_tpu.utils.chaos import g_chaos
    from open_source_search_engine_tpu.utils.stats import g_stats

    seed = int(os.environ.get("OSSE_CHAOS", "0") or 0) or 1234
    n_pages = int(os.environ.get("BENCH_SOAK_PAGES", "48"))
    n_q = int(os.environ.get("BENCH_SOAK_QUERIES", "160"))
    p99_bound_ms = float(os.environ.get("BENCH_SOAK_P99_MS", "5000"))
    bdir = os.environ.get("BENCH_DIR") or tempfile.mkdtemp(
        prefix="osse_soak_")

    g_stats.reset()
    g_chaos.disable()

    # --- the cluster: 2 shards × 2 twins (replica-major hosts.conf) ---
    names = ("a0", "b0", "a1", "b1")
    nodes = [cl.ShardNodeServer(os.path.join(bdir, nm)) for nm in names]
    for n in nodes:
        n.start()
    conf = cl.HostsConf.parse(
        "num-mirrors: 1\n" + "\n".join(
            f"127.0.0.1:{n.port}" for n in nodes))
    client = cl.ClusterClient(conf, use_heartbeat=False)
    client.hostmap.rtt_s[:, 0] = 0.001  # pin replica 0 as primary
    client.hostmap.rtt_s[:, 1] = 0.002

    # --- a synthetic linked web (fixed seed, unique body tokens) ------
    rng = random.Random(6)
    vocab = ["apple", "banana", "cluster", "search", "engine", "chaos",
             "merge", "shard", "twin", "spider", "crawl", "soak"]

    def _url(i: int) -> str:
        return f"http://site{i % 5}.soak.test/p{i}"

    pages = {}
    for i in range(n_pages):
        outl = rng.sample(range(n_pages), min(3, n_pages))
        body = " ".join(rng.choices(vocab, k=24)) + f" token{i}"
        pages[_url(i)] = (
            f"<html><head><title>Soak page {i}</title></head><body>"
            f"<p>{body}</p>"
            + "".join(f'<a href="{_url(j)}">l{j}</a>' for j in outl)
            + "</body></html>")

    class _WebFetcher:
        def fetch_many(self, urls):
            return [FetchResult(url=u, status=200, content=pages[u],
                                content_type="text/html")
                    if u in pages else FetchResult(url=u, status=404)
                    for u in urls]

    local = ShardedCollection("soak", os.path.join(bdir, "grid1"),
                              n_shards=1)

    class _Target:
        """SpiderLoop's sharded-collection duck type: index into the
        crawl-side grid (link harvest) AND tee into the cluster."""

        def index_document(self, url, content, is_html=True,
                           siterank=0):
            ml = local.index_document(url, content, is_html=is_html,
                                      siterank=siterank)
            if ml is not None:
                client.index_document(url, content)
            return ml

        def site_num_inlinks(self, site):
            return local.site_num_inlinks(site)

    sched = SpiderScheduler(
        filters=[UrlFilterRule("*", delay_s=0.005)],
        resolver=lambda host: host)
    loop = SpiderLoop(_Target(), scheduler=sched, fetcher=_WebFetcher(),
                      batch_size=8)
    for i in range(n_pages):
        loop.add_url(_url(i))
    t0 = time.perf_counter()
    crawl_stats = loop.crawl(max_pages=n_pages, max_steps=n_pages * 4)
    crawl_s = time.perf_counter() - t0

    # two on-disk runs per node so the merge sweep has real work, and
    # everything indexed survives the mid-soak node kill/restart
    for n in nodes:
        n.coll.posdb.dump()
    for i in range(min(6, n_pages)):
        client.index_document(_url(i), pages[_url(i)])
    for n in nodes:
        n.coll.posdb.dump()

    # --- arm chaos, then the open-loop Zipf query load ----------------
    # aim wire faults at b1 (shard 1's backup twin): hedged legs absorb
    # them without query loss
    g_chaos.enable(seed, rate=0.0)
    g_chaos.configure("transport.request", rate=0.15,
                      kinds=("delay", "refuse"),
                      match=f"127.0.0.1:{nodes[3].port}", delay_s=0.01)

    distinct = vocab + [f"token{i}" for i in range(n_pages)]
    zipf = [1.0 / (r + 1) ** 1.1 for r in range(len(distinct))]
    qs = rng.choices(distinct, weights=zipf, k=n_q)
    kill_at = max(1, n_q // 3)
    # unique multi-term query: never result-cached, so its scatter leg
    # reaches the doomed primary
    qs[kill_at] = f"cluster token{kill_at % n_pages}"

    lats, lost, degraded = [], 0, 0
    kill_armed = False
    for k, q in enumerate(qs):
        if k == kill_at:
            g_chaos.configure("cluster.node", rate=1.0, kinds=("kill",),
                              match=str(nodes[0].port), delay_s=0.05)
            kill_armed = True
        dl = None
        if k % 9 == 4:
            # born-tight budget on a never-cached query: must come back
            # degraded (the abandon path), never lost
            dl = dlmod.Deadline.after(0.0003)
            q = f"{q} tight{k}"
        q0 = time.perf_counter()
        try:
            with dlmod.bind(dl):
                res = client.search(q, topk=10)
        except Exception:
            lost += 1
            continue
        lats.append(1000.0 * (time.perf_counter() - q0))
        if res is None:
            lost += 1
        elif getattr(res, "degraded", False):
            degraded += 1
        if kill_armed and g_chaos.fired("cluster.node").get("kill", 0):
            g_chaos.configure("cluster.node", rate=0.0)  # one kill only
            kill_armed = False
    kill_count = g_chaos.fired("cluster.node").get("kill", 0)
    g_chaos.configure("transport.request", rate=0.0)

    # --- recovery: restart the killed node, heartbeats revive it ------
    restarted = cl.ShardNodeServer(os.path.join(bdir, "a0"),
                                   port=nodes[0].port)
    give_up = dlmod.Deadline.after(10.0)
    while True:
        try:
            restarted.start()
            break
        except OSError:  # socket still draining from the kill
            if give_up.expired():
                raise
            time.sleep(0.05)
    nodes[0] = restarted
    for _ in range(3):
        client.check_hosts()
    recovered = bool(client.hostmap.alive.all())

    # --- corruption: flip a byte on disk; scrub must trip FIRST -------
    victim = nodes[1].coll.posdb
    flipped = g_chaos.corrupt_one_run(victim)
    quarantined = victim.scrub()
    post = client.search("cluster soak probe", topk=5)
    served_after_scrub = post is not None and not getattr(
        post, "degraded", False)

    # --- forced merge sweep under forced memory pressure --------------
    g_chaos.configure("membudget.reserve", rate=1.0,
                      kinds=("pressure",))
    import types
    dm = DailyMerge([n.coll for n in nodes],
                    types.SimpleNamespace(merge_quiet_hours="0-23"),
                    check_interval_s=3600)
    dm.tick(now=datetime(2026, 1, 5, 12, 0))
    g_chaos.configure("membudget.reserve", rate=0.0)
    pressure = g_chaos.fired("membudget.reserve").get("pressure", 0)

    # --- grow the crawl grid: rebalance 1 → 2 shards ------------------
    docs_before = local.num_docs
    grid2 = rebalance("soak", local, os.path.join(bdir, "grid2"),
                      old_n_shards=1, new_n_shards=2)
    docs_after = grid2.num_docs

    g_chaos.disable()
    c = g_stats.snapshot()["counters"]
    lats.sort()

    def pct(q):
        return lats[min(len(lats) - 1, int(len(lats) * q))] if lats \
            else float("inf")

    gates = {
        "crawl_complete": crawl_stats.indexed == n_pages,
        "zero_lost_queries": lost == 0,
        "hedge_ate_kill": (kill_count >= 1
                           and c.get("transport.hedge_fired", 0) >= 1
                           and c.get("transport.hedge_won", 0) >= 1),
        "deadline_abandoned": c.get("deadline.abandoned", 0) > 0,
        "corruption_quarantined": (flipped is not None
                                   and len(quarantined) > 0
                                   and c.get("rdb.corrupt_quarantined",
                                             0) >= 1
                                   and served_after_scrub),
        "merge_ran_under_pressure": dm.merges >= 1 and pressure >= 1,
        "rebalance_conserved_docs": (docs_before == docs_after
                                     and docs_before > 0),
        "twin_recovered": recovered,
        "p99_bounded": pct(0.99) <= p99_bound_ms,
    }
    ok = all(gates.values())
    keep = ("chaos.", "deadline.", "transport.", "results.", "rdb.",
            "cluster.")
    rep = {
        "metric": "soak_gate", "value": int(ok), "unit": "pass",
        "ok": ok, "gates": gates, "seed": seed,
        "lost_queries": lost, "degraded_queries": degraded,
        "queries": n_q, "pages": crawl_stats.indexed,
        "crawl_s": round(crawl_s, 2),
        "p50_ms": round(pct(0.50), 1), "p99_ms": round(pct(0.99), 1),
        "merges": dm.merges,
        "counters": {k: v for k, v in sorted(c.items())
                     if k.startswith(keep)},
    }
    rep.update(_backend_record())
    print(json.dumps(rep))
    for n in nodes:
        n.stop()
    client.close()
    return rep


def main_slo() -> dict:
    """SLO gate (BENCH_SLO=1): a closed-loop query run on a 2-node
    in-process cluster with ONE declared objective (query p99 <
    BENCH_SLO_P99_MS). Scrapes ride the run at a fixed cadence and
    feed the tracker the merged fleet stream. Exits 1 unless EVERY
    gate holds: the merged fleet histogram is non-empty, the
    burn-rate/budget math is finite, and total scrape time stays
    under 1% of query wall time. Prints ONE JSON line; returns the
    report."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import math
    import random

    from open_source_search_engine_tpu.parallel import cluster as cl
    from open_source_search_engine_tpu.utils.slo import SloTracker
    from open_source_search_engine_tpu.utils.stats import g_stats

    g_stats.reset()
    bdir = tempfile.mkdtemp(prefix="osse_bench_slo_")
    n_docs = int(os.environ.get("BENCH_SLO_DOCS", "24"))
    n_q = int(os.environ.get("BENCH_SLO_QUERIES", "400"))
    p99_ms = float(os.environ.get("BENCH_SLO_P99_MS", "500"))
    # two scrapes per run: the sampler's production cadence is one per
    # 10s tick, so a sub-second closed loop gets mid-run + end-of-run
    scrape_every = max(1, n_q // 2)
    vocab = ("alpha bravo charlie delta echo foxtrot golf hotel "
             "india juliet kilo lima").split()
    nodes = []
    for i in range(2):
        node = cl.ShardNodeServer(os.path.join(bdir, f"n{i}"))
        for d in range(n_docs):
            words = " ".join(vocab[(d + j) % len(vocab)]
                             for j in range(5))
            node.handle("/rpc/index", {
                "url": f"http://slo.test/{i}-{d}",
                "content": (f"<html><body><p>{words} "
                            f"token{d}</p></body></html>")})
        node.start()
        nodes.append(node)
    conf = cl.HostsConf.parse(
        "num-mirrors: 0\n"
        + "\n".join(f"127.0.0.1:{n.port}" for n in nodes))
    client = cl.ClusterClient(conf, use_heartbeat=False)

    slo = SloTracker(registry=g_stats)
    slo.declare_latency("query_p99", "cluster.query",
                        threshold_ms=p99_ms, target=0.99)

    rng = random.Random(6)
    distinct = vocab + [f"token{d}" for d in range(n_docs)]
    weights = [1.0 / (r + 1) ** 1.1 for r in range(len(distinct))]
    # two-term queries: the pair space is large enough that most of
    # the stream misses the result cache and pays a real scatter
    stream = [" ".join(rng.choices(distinct, weights=weights, k=2))
              for _ in range(n_q)]
    for q in stream[:8]:  # absorb JAX compiles before the timed loop
        client.search(q, topk=10)

    fleet = None
    scrape_s = 0.0
    t0 = time.perf_counter()
    for k, q in enumerate(stream):
        client.search(q, topk=10)
        if (k + 1) % scrape_every == 0:
            s0 = time.perf_counter()
            fleet = client.scrape()["fleet"]
            scrape_s += time.perf_counter() - s0
            slo.evaluate(fleet["counters"], fleet["latencies"])
    wall = time.perf_counter() - t0

    st = slo.status().get("query_p99", {})
    hist = (fleet or {}).get("latencies", {}).get("cluster.query")
    overhead = scrape_s / max(wall, 1e-9)
    gates = {
        "fleet_histogram_nonempty": (hist is not None
                                     and hist.count > 0),
        "burn_math_finite": (
            math.isfinite(st.get("burn_rate", float("nan")))
            and math.isfinite(st.get("budget_remaining",
                                     float("nan")))),
        "scrape_overhead_under_1pct": overhead < 0.01,
    }
    ok = all(gates.values())
    rep = {
        "metric": "slo_gate", "value": int(ok), "unit": "pass",
        "ok": ok, "gates": gates, "queries": n_q,
        "fleet_query_count": 0 if hist is None else hist.count,
        "fleet_p99_ms": (0.0 if hist is None
                         else round(hist.quantile(0.99), 2)),
        "burn_rate": round(st.get("burn_rate", -1.0), 4),
        "budget_remaining": round(st.get("budget_remaining", -1.0), 4),
        "scrape_overhead_pct": round(100.0 * overhead, 3),
        "wall_s": round(wall, 2),
    }
    rep.update(_backend_record())
    print(json.dumps(rep))
    client.close()
    for n in nodes:
        n.stop()
    return rep


def main_load() -> dict:
    """Open-loop load gate (BENCH_LOAD=1): thousands of simulated
    clients offer Poisson arrivals of a Zipf query mix to the serving
    front door at swept rates — OPEN loop, so offered load does not
    politely slow down when the server does (the closed-loop benches
    can never create overload; this one exists to). Legs:

    1. sweep BENCH_LOAD_QPS ascending → max sustained qps with fleet
       p99 < BENCH_LOAD_P99_MS (from ``ClusterClient.scrape()``);
    2. overload at BENCH_LOAD_OVER_X × the gate's measured capacity
       (max_inflight / svc EWMA), with a mid-leg 2× burst: interactive
       p99 must stay bounded while crawlbot traffic sheds, every shed
       counted, and the admission queue must drain afterwards (no
       metastable collapse);
    3. recovery at the lowest sweep rate: p99 back under the SLO.

    Chaos slow-walks every node (deterministic service-time floor) so
    capacity is bounded by the admission plane, not scheduler noise.
    Exits 1 unless EVERY gate holds. Prints ONE JSON line."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import random
    import threading
    from collections import Counter
    from concurrent.futures import ThreadPoolExecutor

    from open_source_search_engine_tpu.parallel import cluster as cl
    from open_source_search_engine_tpu.serve import admission as adm
    from open_source_search_engine_tpu.serve.server import \
        SearchHTTPServer
    from open_source_search_engine_tpu.utils.chaos import g_chaos
    from open_source_search_engine_tpu.utils.stats import g_stats

    g_stats.reset()
    bdir = tempfile.mkdtemp(prefix="osse_bench_load_")
    n_docs = int(os.environ.get("BENCH_LOAD_DOCS", "16"))
    sweep = [float(x) for x in
             os.environ.get("BENCH_LOAD_QPS", "8,16,32").split(",")]
    leg_s = float(os.environ.get("BENCH_LOAD_SECONDS", "3"))
    p99_ms = float(os.environ.get("BENCH_LOAD_P99_MS", "500"))
    over_p99_ms = float(os.environ.get("BENCH_LOAD_OVER_P99_MS",
                                       "1500"))
    over_x = float(os.environ.get("BENCH_LOAD_OVER_X", "2"))
    delay_ms = float(os.environ.get("BENCH_LOAD_DELAY_MS", "20"))
    deadline_ms = float(os.environ.get("BENCH_LOAD_DEADLINE_MS",
                                       "400"))
    n_clients = int(os.environ.get("BENCH_LOAD_CLIENTS", "2000"))
    workers = int(os.environ.get("BENCH_LOAD_WORKERS", "64"))

    vocab = ("alpha bravo charlie delta echo foxtrot golf hotel "
             "india juliet kilo lima").split()
    nodes = []
    for i in range(2):
        node = cl.ShardNodeServer(os.path.join(bdir, f"n{i}"))
        for d in range(n_docs):
            words = " ".join(vocab[(d + j) % len(vocab)]
                             for j in range(5))
            node.handle("/rpc/index", {
                "url": f"http://load.test/{i}-{d}",
                "content": (f"<html><body><p>{words} "
                            f"token{d}</p></body></html>")})
        node.start()
        nodes.append(node)
    conf = cl.HostsConf.parse(
        "num-mirrors: 0\n"
        + "\n".join(f"127.0.0.1:{n.port}" for n in nodes))
    client = cl.ClusterClient(conf, use_heartbeat=False)
    srv = SearchHTTPServer(os.path.join(bdir, "front"),
                           cluster=client)
    # a tight, deterministic gate: capacity = max_inflight / svc time,
    # so the harness can oversubscribe it on any machine
    srv.admission = adm.AdmissionGate(max_inflight=2, max_queue=32)
    if delay_ms > 0:
        # chaos under offered load: slow-walk every node leg so the
        # service-time floor (and therefore capacity) is deterministic
        g_chaos.enable(11, rate=0.0)
        g_chaos.configure("cluster.node", rate=1.0,
                          kinds=("slowwalk",),
                          delay_s=delay_ms / 1000.0)

    rng = random.Random(6)
    distinct = vocab + [f"token{d}" for d in range(n_docs)]
    zipf_w = [1.0 / (r + 1) ** 1.1 for r in range(len(distinct))]
    #: simulated client population: each has a sticky ip + tier
    #: (60/10/30 interactive/suggest/crawlbot)
    clients = [((f"10.{k >> 16 & 255}.{k >> 8 & 255}.{k & 255}"),
                rng.choices(("interactive", "suggest", "crawlbot"),
                            weights=(0.6, 0.1, 0.3))[0])
               for k in range(1, n_clients + 1)]

    for w in vocab[:8]:  # absorb JAX compiles before any timed leg
        srv.handle("GET", "/search", {"q": w}, b"",
                   client_ip="10.0.0.0")

    pool = ThreadPoolExecutor(workers)
    lock = threading.Lock()

    def one(qstr: str, tier: str, ip: str, counts: Counter) -> None:
        try:
            code, _, _ = srv.handle(
                "GET", "/search",
                {"q": qstr, "tier": tier,
                 "deadline_ms": str(deadline_ms)},
                b"", client_ip=ip)
        except Exception:  # noqa: BLE001 — a lost reply is the bug
            code = -1
        with lock:
            counts[(tier, code)] += 1

    def run_leg(qps: float, seconds: float,
                burst_x: float = 1.0) -> dict:
        g_stats.reset()
        counts: Counter = Counter()
        futs = []
        t_start = time.monotonic()
        end = t_start + seconds
        b_lo = t_start + seconds / 3.0
        b_hi = t_start + 2.0 * seconds / 3.0
        t_next = t_start
        arrivals = 0
        while t_next < end:
            now = time.monotonic()
            if t_next > now:
                time.sleep(t_next - now)
            q = " ".join(rng.choices(distinct, weights=zipf_w, k=2))
            ip, tier = clients[rng.randrange(n_clients)]
            futs.append(pool.submit(one, q, tier, ip, counts))
            arrivals += 1
            rate = qps * (burst_x if b_lo <= t_next < b_hi else 1.0)
            t_next += rng.expovariate(rate)
        for f in futs:
            f.result()
        fleet = client.scrape()["fleet"]
        # counters come from the LOCAL registry: in-process nodes share
        # it, so the fleet merge double-counts front-door counters
        counters = g_stats.snapshot()["counters"]

        def p99(name: str) -> float:
            h = fleet["latencies"].get(name)
            return round(h.quantile(0.99), 2) if h is not None \
                and h.count else 0.0

        by_code: Counter = Counter()
        by_tier_code: dict = {}
        for (tier, code), n in counts.items():
            by_code[code] += n
            by_tier_code.setdefault(tier, Counter())[code] += n
        return {
            "offered_qps": round(qps, 1), "arrivals": arrivals,
            "responses": sum(counts.values()),
            "p99_ms": p99("serve.search"),
            "tier_p99_ms": {t: p99(f"serve.search.{t}")
                            for t in ("interactive", "suggest",
                                      "crawlbot")},
            "codes": {str(c): n for c, n in sorted(by_code.items())},
            "tier_codes": {t: {str(c): n for c, n in sorted(v.items())}
                           for t, v in sorted(by_tier_code.items())},
            "shed_stale": counters.get("admission.shed.stale", 0),
            "shed_refused": counters.get("admission.shed.refused", 0),
            "queue_full": counters.get("admission.queue_full", 0),
            "membudget_reject_serve": counters.get(
                "membudget.reject.serve", 0),
            "queue_delay_p99_ms": p99("admission.queue_delay"),
        }

    # --- leg 1: the sweep -------------------------------------------------
    legs = []
    max_sustained = 0.0
    for qps in sweep:
        leg = run_leg(qps, leg_s)
        ok = (leg["p99_ms"] < p99_ms
              and leg["responses"] == leg["arrivals"])
        leg["sustained"] = ok
        legs.append(leg)
        if ok:
            max_sustained = qps
    sweep_hist_nonempty = any(leg["p99_ms"] > 0 for leg in legs)

    # --- leg 2: overload (offered >> capacity, with a burst) --------------
    snap = srv.admission.snapshot()
    capacity = srv.admission.max_inflight / max(
        snap["svc_ewma_ms"] / 1000.0, 1e-3)
    over_qps = max(over_x * capacity, 2.0 * max(sweep))
    over = run_leg(over_qps, leg_s, burst_x=2.0)
    crawl_503 = over["tier_codes"].get("crawlbot", {}).get("503", 0)
    crawl_shed = crawl_503 + over["shed_stale"]
    drained = False
    t0 = time.monotonic()
    while time.monotonic() - t0 < 5.0:
        if srv.admission.idle():
            drained = True
            break
        time.sleep(0.02)

    # --- leg 3: recovery --------------------------------------------------
    recovery = run_leg(min(sweep), leg_s)

    gates = {
        "max_sustained_qps_positive": max_sustained > 0,
        "fleet_histogram_nonempty": sweep_hist_nonempty,
        "overload_actually_shed": over["shed_refused"]
        + over["shed_stale"] > 0,
        "overload_interactive_p99_bounded":
            0 < over["tier_p99_ms"]["interactive"] < over_p99_ms,
        "overload_crawlbot_shed": crawl_shed > 0,
        "all_sheds_counted": (
            over["responses"] == over["arrivals"]
            and over["codes"].get("503", 0) == over["shed_refused"]
            and over["codes"].get("-1", 0) == 0),
        "queue_drained_post_burst": drained,
        "shed_before_membudget_refusal":
            over["membudget_reject_serve"] == 0,
        "recovery_p99_ok": (0 < recovery["p99_ms"] < p99_ms
                            and recovery["responses"]
                            == recovery["arrivals"]),
    }
    ok = all(gates.values())
    rep = {
        "metric": "load_gate", "value": round(max_sustained, 1),
        "unit": "qps_at_p99_lt_%dms" % int(p99_ms),
        "ok": ok, "gates": gates,
        "max_sustained_qps": round(max_sustained, 1),
        "capacity_est_qps": round(capacity, 1),
        "sweep": legs, "overload": over, "recovery": recovery,
    }
    rep.update(_backend_record())
    print(json.dumps(rep))
    pool.shutdown(wait=False)
    g_chaos.disable()
    srv.stop()
    client.close()
    for n in nodes:
        n.stop()
    return rep


def main_fleet() -> dict:
    """Fleet gate (BENCH_FLEET=1): a 2-shard × 2-twin fleet of REAL OS
    processes (``parallel.fleet.FleetManager``) serves an open-loop
    Zipf query stream while the legs fire in sequence:

    1. survive-the-primary: mid-load writes land (acked + journaled on
       every twin), then chaos WEDGES (SIGSTOP, the ``fleet.wedge``
       seam) shard 0's primary so in-flight requests sit silently —
       the transport's hedge timer must fire and the twin must win,
       with zero lost responses — and finally kills the wedged process
       for real (SIGKILL, the ``fleet.kill`` seam);
    2. rejoin: the supervisor respawns the corpse from its checkpoint
       dir; journal replay must conserve every acked doc (twin
       equality AND fleet total) and the scrape must see all hosts up;
    3. rolling restart under load: every node drains through its
       admission gate, checkpoints via /rpc/save, restarts — p99 stays
       inside BENCH_FLEET_P99_MS, nothing is lost, every node reports
       drained+saved;
    4. parm broadcast: applied on every node live (pids unchanged —
       the reference's 0x3f update, no restarts);
    5. shard split, cross-process: after teardown the fleet's on-disk
       grid re-shards 2 → 3 via control.rebalance, docs conserved.

    Exits 1 unless EVERY gate holds. Prints ONE JSON line."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import random
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from open_source_search_engine_tpu.control.rebalance import rebalance
    from open_source_search_engine_tpu.parallel import cluster as cl
    from open_source_search_engine_tpu.parallel.fleet import FleetManager
    from open_source_search_engine_tpu.utils.chaos import g_chaos
    from open_source_search_engine_tpu.utils.stats import g_stats

    g_stats.reset()
    bdir = tempfile.mkdtemp(prefix="osse_bench_fleet_")
    n_docs = int(os.environ.get("BENCH_FLEET_DOCS", "12"))
    n_mid = int(os.environ.get("BENCH_FLEET_MID_WRITES", "4"))
    qps = float(os.environ.get("BENCH_FLEET_QPS", "10"))
    leg_s = float(os.environ.get("BENCH_FLEET_SECONDS", "8"))
    p99_ms = float(os.environ.get("BENCH_FLEET_P99_MS", "5000"))
    workers = int(os.environ.get("BENCH_FLEET_WORKERS", "16"))

    vocab = ("alpha bravo charlie delta echo foxtrot golf hotel "
             "india juliet kilo lima").split()

    def html_of(d: int) -> str:
        words = " ".join(vocab[(d + j) % len(vocab)] for j in range(5))
        return (f"<html><head><title>Fleet doc {d}</title></head>"
                f"<body><p>{words} token{d}</p></body></html>")

    grid_dir = os.path.join(bdir, "grid")
    fm = FleetManager(grid_dir, n_shards=2, n_replicas=2,
                      chaos_seed=11)
    g_chaos.enable(11, rate=0.0)  # parent seams armed, aimed-only
    pool = ThreadPoolExecutor(workers)
    lock = threading.Lock()
    rng = random.Random(7)
    try:
        fm.start_all()
        client = cl.ClusterClient(fm.conf, use_heartbeat=False)
        for d in range(n_docs):
            client.index_document(f"http://fleet.test/{d}", html_of(d))
        seeded_ok = client.pending_writes == 0

        # warm every node's query path DIRECTLY (first /rpc/search
        # compiles ~1s; it must inflate neither the hedge EWMA nor a
        # timed leg), then pin the twin order: replica 0 primary
        for addr in fm.addrs():
            client.transport.request(addr, "/rpc/search",
                                     {"q": "alpha bravo", "topk": 5},
                                     timeout=120.0)
        client.search("alpha bravo", topk=5, site_cluster=False)
        for s in range(fm.n_shards):
            client.hostmap.rtt_s[s, 0] = 0.001
            client.hostmap.rtt_s[s, 1] = 0.002

        distinct = vocab + [f"token{d}" for d in range(n_docs)]
        zipf_w = [1.0 / (r + 1) ** 1.1 for r in range(len(distinct))]

        def run_leg(seconds: float, during=(), stop_when=None) -> dict:
            """Open-loop Poisson arrivals at ``qps``; each ``during``
            entry ``(frac, fn)`` fires once as the leg crosses that
            fraction of its span. A lost response (exception out of
            the hedged scatter) is the bug this gate exists to catch."""
            lats: list[float] = []
            counts = {"ok": 0, "degraded": 0, "lost": 0}
            events = sorted(during)
            futs = []

            def one(qstr: str) -> None:
                t0 = time.monotonic()
                try:
                    res = client.search(qstr, topk=5,
                                        site_cluster=False)
                    key = "degraded" if res.degraded else "ok"
                except Exception:  # noqa: BLE001 — a lost reply
                    key = "lost"
                dt = time.monotonic() - t0
                with lock:
                    counts[key] += 1
                    lats.append(dt)

            t_start = time.monotonic()
            end = t_start + seconds
            t_next = t_start
            arrivals = 0
            ei = 0
            while t_next < end and not (stop_when and stop_when()):
                now = time.monotonic()
                if t_next > now:
                    time.sleep(t_next - now)
                frac = (time.monotonic() - t_start) / seconds
                while ei < len(events) and frac >= events[ei][0]:
                    events[ei][1]()
                    ei += 1
                q = " ".join(rng.choices(distinct, weights=zipf_w,
                                         k=2))
                futs.append(pool.submit(one, q))
                arrivals += 1
                t_next += rng.expovariate(qps)
            for f in futs:
                f.result()
            while ei < len(events):  # leg too short for an event frac
                events[ei][1]()
                ei += 1
            p99 = (float(np.percentile(np.asarray(lats) * 1000.0, 99))
                   if lats else 0.0)
            return {"arrivals": arrivals, **counts,
                    "p99_ms": round(p99, 1)}

        # --- leg 1: mid-load writes → wedge → real SIGKILL ---------------
        prey: dict = {}

        def mid_writes() -> None:
            for d in range(n_docs, n_docs + n_mid):
                client.index_document(f"http://fleet.test/{d}",
                                      html_of(d))

        def wedge_primary() -> None:
            g_chaos.configure("fleet", rate=1.0, kinds=("wedge",))
            prey["pid"] = fm.pid(0, 0)
            prey["wedge"] = g_chaos.fleet_fault(prey["pid"])

        def kill_primary() -> None:
            g_chaos.configure("fleet", rate=1.0, kinds=("kill",))
            prey["kill"] = g_chaos.fleet_fault(prey["pid"])

        c0 = g_stats.snapshot()["counters"]
        leg1 = run_leg(leg_s, during=[(0.25, mid_writes),
                                      (0.45, wedge_primary),
                                      (0.70, kill_primary)])
        c1 = g_stats.snapshot()["counters"]
        hedge_fired = (c1.get("transport.hedge_fired", 0)
                       - c0.get("transport.hedge_fired", 0))
        hedge_won = (c1.get("transport.hedge_won", 0)
                     - c0.get("transport.hedge_won", 0))

        # --- leg 2: supervisor respawn + journal replay + rejoin ---------
        ping00 = fm.wait_ready(0, 0, timeout_s=60.0)
        ping01 = fm.transport.request(fm.addr(0, 1), "/rpc/ping", {},
                                      timeout=10.0)
        ping10 = fm.transport.request(fm.addr(1, 0), "/rpc/ping", {},
                                      timeout=10.0)
        total_docs = n_docs + n_mid
        docs_conserved = (ping00["docs"] == ping01["docs"]
                          and ping00["docs"] + ping10["docs"]
                          == total_docs)
        def hosts_up_now() -> int:
            sc = client.scrape()
            return sum(1 for w in sc["hosts"].values()
                       if w is not None)

        # the first scrape after a respawn can ride a pooled connection
        # that died with the old process — re-scrape briefly before
        # calling a host down (a scrape is a read, not a liveness
        # verdict)
        hosts_up = hosts_up_now()
        scrape_end = time.monotonic() + 15.0
        while (hosts_up < fm.n_shards * fm.n_replicas
               and time.monotonic() < scrape_end):
            time.sleep(0.25)
            hosts_up = hosts_up_now()

        # --- leg 3: rolling restart under load ---------------------------
        roll: dict = {}

        def do_roll() -> None:
            roll.update(fm.rolling_restart(drain_timeout_s=5.0))

        roll_fut = pool.submit(do_roll)
        leg3 = run_leg(120.0, stop_when=roll_fut.done)
        roll_fut.result()
        roll_ok = bool(roll.get("nodes")) and all(
            n["drained"] and n["saved"] for n in roll["nodes"])

        # --- leg 4: live parm broadcast (no restarts) --------------------
        pids_before = dict(fm.pids())
        replies = fm.broadcast_parms({"spider_delay_ms": 4321})
        parm_applied = all(
            r is not None and r.get("ok")
            and "spider_delay_ms" in r.get("applied", [])
            for r in replies.values())
        conf_ok = all(
            (fm.transport.request(a, "/rpc/conf", {}, timeout=10.0)
             or {}).get("conf", {}).get("spider_delay_ms") == 4321
            for a in fm.addrs())
        parm_no_restart = dict(fm.pids()) == pids_before

        client.close()
    finally:
        fm.shutdown()
        g_chaos.disable()
        pool.shutdown(wait=False)
    reaped = fm.surviving_pids() == []

    # --- leg 5: cross-process shard split on the shut-down grid ---------
    sc = rebalance("shard", grid_dir, os.path.join(bdir, "regrid"),
                   2, 3)
    rebalance_docs = int(sc.num_docs)

    gates = {
        "seed_writes_acked": seeded_ok,
        "kill_leg_zero_lost": leg1["lost"] == 0
        and leg1["degraded"] == 0,
        "wedge_hedge_fired_and_won": prey.get("wedge") == "wedge"
        and hedge_fired > 0 and hedge_won > 0,
        "killed_for_real": prey.get("kill") == "kill",
        "rejoin_replayed_docs_conserved": docs_conserved,
        "rejoin_new_pid": ping00["pid"] != prey.get("pid"),
        "scrape_all_hosts_up": hosts_up
        == fm.n_shards * fm.n_replicas,
        "rolling_restart_drained_and_saved": roll_ok,
        "rolling_restart_zero_lost": leg3["lost"] == 0
        and leg3["degraded"] == 0,
        "rolling_restart_p99_in_slo": 0 < leg3["p99_ms"] < p99_ms,
        "parm_applied_everywhere": parm_applied and conf_ok,
        "parm_without_restart": parm_no_restart,
        "teardown_no_orphans": reaped,
        "rebalance_docs_conserved": rebalance_docs == total_docs,
    }
    ok = all(gates.values())
    rep = {
        "metric": "fleet_gate",
        "value": sum(bool(v) for v in gates.values()),
        "unit": f"gates_passed_of_{len(gates)}",
        "ok": ok, "gates": gates,
        "kill_leg": leg1, "roll_leg": leg3, "roll": roll,
        "hedge_fired": hedge_fired, "hedge_won": hedge_won,
        "hosts_up": hosts_up, "sheds": roll.get("sheds", 0),
        "docs_total": total_docs, "rebalance_docs": rebalance_docs,
    }
    rep.update(_backend_record())
    print(json.dumps(rep))
    return rep


def main_tenants() -> dict:
    """Tenant-plane gate (BENCH_TENANTS=1): ONE front door serving a
    Zipf(s=1.5) query mix over BENCH_TENANTS_COLLS collections with a
    residency budget of BENCH_TENANTS_HOT — far below the collection
    count, so the ResidencyManager must keep the hot head device-
    resident while the cold tail churns through promote/park. Legs:

    1. Zipf leg (sequential, seeded, so the LRU trace is reproducible):
       every arrival must answer 200 with zero admission sheds, the
       residency hit rate must clear BENCH_TENANTS_HIT_RATE, cold-start
       p99 must stay under BENCH_TENANTS_COLD_P99_MS (compiles are
       absorbed on a throwaway collection first, so the bound measures
       transfer+build, not XLA), the resident count must respect the
       budget, and the membudget must never refuse (parking IS the
       relief valve);
    2. quota leg: a tight swapped-in AdmissionGate(1 inflight/4 queue)
       while one tenant floods and another trickles — weighted-fair
       queueing must keep the quiet tenant shed-free while the flood
       eats quota sheds (including displacement of its own waiters).

    Exits 1 unless EVERY gate holds. Prints ONE JSON line."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import random
    import threading
    from collections import Counter

    from open_source_search_engine_tpu.build import docproc
    from open_source_search_engine_tpu.serve import admission as adm
    from open_source_search_engine_tpu.serve.server import \
        SearchHTTPServer
    from open_source_search_engine_tpu.serve.tenancy import g_residency
    from open_source_search_engine_tpu.utils.stats import g_stats

    n_colls = int(os.environ.get("BENCH_TENANTS_COLLS", "1000"))
    hot = int(os.environ.get("BENCH_TENANTS_HOT", "160"))
    n_q = int(os.environ.get("BENCH_TENANTS_QUERIES", "2000"))
    hit_gate = float(os.environ.get("BENCH_TENANTS_HIT_RATE", "0.85"))
    cold_p99_ms = float(os.environ.get("BENCH_TENANTS_COLD_P99_MS",
                                       "2500"))
    bdir = tempfile.mkdtemp(prefix="osse_bench_tenants_")
    srv = SearchHTTPServer(bdir)

    words = "walrus herd colony shore tusk haulout".split()
    names = [f"t{i:04d}" for i in range(n_colls)]
    t_build = time.monotonic()
    for i, name in enumerate(names):
        coll = srv.colldb.get(name)
        # cache off so every request reaches the engine (the leg
        # measures RESIDENCY hits, not the result cache); pqr off so
        # a cold start is index build + transfer, nothing else
        coll.conf.result_cache_ttl = 0
        coll.conf.pqr_enabled = False
        docproc.index_document(
            coll, f"http://tenants.test/{name}",
            f"<html><body><p>{' '.join(words)} doc{i}</p>"
            "</body></html>")
    build_s = time.monotonic() - t_build

    # absorb the one-time JAX compile on a throwaway tenant, then wipe
    # the residency ledger so the timed leg starts cold and its
    # cold-start histogram never sees the compile wall
    wcoll = srv.colldb.get("_warmup")
    wcoll.conf.result_cache_ttl = 0
    wcoll.conf.pqr_enabled = False
    docproc.index_document(wcoll, "http://tenants.test/_warmup",
                           "<html><body><p>walrus warm</p></body>"
                           "</html>")
    for _ in range(3):
        srv.handle("GET", "/search", {"q": "walrus", "c": "_warmup"},
                   b"")
    g_residency.reset()  # also parks _warmup; reset zeroes the knob...
    g_residency.configure(max_resident=hot)  # ...so rearm the budget
    g_stats.reset()

    # --- leg 1: Zipf over the collection space ----------------------------
    # the ONLY rng draw per query is the collection pick, so the LRU
    # hit/cold trace is a pure function of (n_colls, hot, n_q, seed)
    # and the gate threshold can be calibrated offline
    rng = random.Random(23)
    zipf_w = [1.0 / (r + 1) ** 1.5 for r in range(n_colls)]
    idx = list(range(n_colls))
    codes: Counter = Counter()
    t_leg = time.monotonic()
    for qi in range(n_q):
        c = rng.choices(idx, weights=zipf_w, k=1)[0]
        code, _, _ = srv.handle(
            "GET", "/search",
            {"q": words[qi % len(words)], "c": names[c]}, b"")
        codes[code] += 1
    leg_s = time.monotonic() - t_leg
    counters = g_stats.snapshot()["counters"]
    res = g_residency.snapshot()
    hits = counters.get("tenancy.hit", 0)
    colds = counters.get("tenancy.coldstart", 0)
    hit_rate = hits / max(hits + colds, 1)
    mem_rejects = sum(v for k, v in counters.items()
                      if k.startswith("membudget.reject."))
    sheds = (counters.get("admission.shed.refused", 0)
             + counters.get("admission.shed.stale", 0))

    # --- leg 2: weighted-fair quotas under a flood ------------------------
    # a gate small enough to saturate from one process: the flood tenant
    # must queue/shed against its OWN share while the trickle tenant
    # passes untouched (collection = tenant on the serve path)
    greedy, quiet = names[0], names[1]
    srv.admission = adm.AdmissionGate(max_inflight=1, max_queue=4)
    qcounts: Counter = Counter()
    qlock = threading.Lock()
    stop = threading.Event()

    def flood() -> None:
        while not stop.is_set():
            try:
                code, _, _ = srv.handle(
                    "GET", "/search", {"q": "walrus", "c": greedy},
                    b"")
            except Exception:  # noqa: BLE001 — a lost reply is the bug
                code = -1
            with qlock:
                qcounts[("greedy", code)] += 1

    floggers = [threading.Thread(target=flood, daemon=True)
                for _ in range(6)]
    for th in floggers:
        th.start()
    time.sleep(0.1)  # let the flood saturate inflight + queue
    for _ in range(25):
        try:
            code, _, _ = srv.handle(
                "GET", "/search", {"q": "walrus", "c": quiet}, b"")
        except Exception:  # noqa: BLE001
            code = -1
        with qlock:
            qcounts[("quiet", code)] += 1
        time.sleep(0.004)
    stop.set()
    for th in floggers:
        th.join(timeout=10.0)
    qcounters = g_stats.snapshot()["counters"]
    quiet_shed = qcounts[("quiet", 503)] + qcounts[("quiet", -1)]
    greedy_shed = qcounters.get(f"admission.tenant.{greedy}.shed", 0)
    quota_sheds = qcounters.get("admission.shed.reason.quota", 0)

    gates = {
        "every_arrival_answered_200": (
            sum(codes.values()) == n_q and codes.get(200, 0) == n_q),
        "no_sheds_at_offered_load": sheds == 0,
        "hot_set_hit_rate": hit_rate >= hit_gate,
        "cold_path_exercised": colds > 0
        and res["coldstarts"] == colds,
        "coldstart_p99_bounded": 0 < res["coldstart_p99_ms"]
        < cold_p99_ms,
        "resident_within_budget": 0 < res["resident"] <= hot,
        "zero_membudget_refusals": mem_rejects == 0,
        "quiet_tenant_never_shed": (
            quiet_shed == 0 and qcounts[("quiet", 200)] == 25),
        "flood_tenant_shed_by_quota": greedy_shed > 0
        and quota_sheds > 0,
        "flood_sheds_all_counted": qcounts[("greedy", -1)] == 0,
    }
    ok = all(gates.values())
    rep = {
        "metric": "tenant_gate", "value": round(hit_rate, 3),
        "unit": "residency_hit_rate", "ok": ok, "gates": gates,
        "collections": n_colls, "hot_budget": hot, "queries": n_q,
        "hits": hits, "cold_starts": colds,
        "coldstart_p50_ms": res["coldstart_p50_ms"],
        "coldstart_p99_ms": res["coldstart_p99_ms"],
        "resident": res["resident"], "parked": res["parked"],
        "device_bytes": res["device_bytes"],
        "build_s": round(build_s, 2), "leg_s": round(leg_s, 2),
        "qps": round(n_q / max(leg_s, 1e-9), 1),
        "quota": {"greedy": {str(c): n for (t, c), n
                             in sorted(qcounts.items()) if t == "greedy"},
                  "quiet": {str(c): n for (t, c), n
                            in sorted(qcounts.items()) if t == "quiet"},
                  "greedy_shed": greedy_shed,
                  "quota_sheds": quota_sheds},
    }
    rep.update(_backend_record())
    print(json.dumps(rep))
    srv.stop()
    g_residency.reset()
    shutil.rmtree(bdir, ignore_errors=True)
    return rep


def main_sched() -> dict:
    """Concurrency gate (BENCH_SCHED=1): deep schedule exploration of
    the five protocol scenario suites — BENCH_SCHED_SCHEDULES seeded
    interleavings each (default 1024, vs check.sh's 64) at the
    configured preemption bound. schedcheck arms at import from
    OSSE_SCHED=1, so when the env var is missing this re-execs itself
    with it set rather than silently exploring nothing.

    Exits 1 on ANY schedule failure; the failing seed + shrunk
    preemption trace goes to stderr so the exact interleaving can be
    replayed. Prints ONE JSON line."""
    if os.environ.get("OSSE_SCHED") != "1":
        env = dict(os.environ, OSSE_SCHED="1")
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)], env)
    from open_source_search_engine_tpu.utils import schedcheck
    from tests import sched_scenarios

    n = int(os.environ.get("BENCH_SCHED_SCHEDULES", "1024"))
    bound = int(os.environ.get("OSSE_SCHED_PREEMPTIONS", "3"))
    t0 = time.monotonic()
    suites, ok = {}, True
    for name in sorted(sched_scenarios.SCENARIOS):
        fn = sched_scenarios.SCENARIOS[name]
        try:
            out = schedcheck.explore(fn, schedules=n,
                                     preemption_bound=bound)
            suites[name] = {"ok": True,
                            "yield_points": out["yield_points"]}
        except schedcheck.ScheduleFailure as f:
            ok = False
            suites[name] = {"ok": False, "seed": f.seed,
                            "error": str(f.error)}
            print(f"[sched] {name}:\n{f}", file=sys.stderr)
    rep = {
        "metric": "sched_gate", "value": n, "unit": "schedules",
        "ok": ok, "suites": suites,
        "schedules_explored": n * len(suites),
        "preemption_bound": bound,
        "elapsed_s": round(time.monotonic() - t0, 2),
    }
    rep.update(_backend_record())
    print(json.dumps(rep))
    return rep


if __name__ == "__main__":
    if not os.environ.get("BENCH_MESH_CHILD"):
        # backend preflight: loud, actionable diagnosis on stderr for
        # the r05 init-failure class; never blocks a CPU run
        try:
            from tools import devdoctor
            devdoctor.preflight()
        except Exception:  # noqa: BLE001 — preflight must not wedge
            pass
    if os.environ.get("BENCH_SOAK"):
        sys.exit(0 if main_soak()["ok"] else 1)
    elif os.environ.get("BENCH_MESH_CHILD"):
        _mesh_child()
    elif os.environ.get("BENCH_MESH"):
        sys.exit(0 if main_mesh()["ok"] else 1)
    elif os.environ.get("BENCH_TRANSPORT"):
        main_transport()
    elif os.environ.get("BENCH_CACHE"):
        main_cache()
    elif os.environ.get("BENCH_TRACE"):
        main_trace()
    elif os.environ.get("BENCH_DISPATCH"):
        main_dispatch()
    elif os.environ.get("BENCH_JIT"):
        main_jit()
    elif os.environ.get("BENCH_BUILD"):
        sys.exit(0 if main_build()["ok"] else 1)
    elif os.environ.get("BENCH_SLO"):
        sys.exit(0 if main_slo()["ok"] else 1)
    elif os.environ.get("BENCH_LOAD"):
        sys.exit(0 if main_load()["ok"] else 1)
    elif os.environ.get("BENCH_FLEET"):
        sys.exit(0 if main_fleet()["ok"] else 1)
    elif os.environ.get("BENCH_TENANTS"):
        sys.exit(0 if main_tenants()["ok"] else 1)
    elif os.environ.get("BENCH_DEVOBS"):
        sys.exit(0 if main_devobs()["ok"] else 1)
    elif os.environ.get("BENCH_SCHED"):
        sys.exit(0 if main_sched()["ok"] else 1)
    else:
        main()
