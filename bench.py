"""Benchmark: query throughput on one device vs the reference baseline.

Reference baseline (BASELINE.md / ``html/faq.html:320``): ~8 queries/sec
on a 10M-page index on 2010-era hardware (dual quad-core, 8 gb
instances). BASELINE.json's measurable config here: conjunctive AND +
single-term queries over a synthetic corpus on one chip — the
``PosdbTable::intersectLists10_r`` path (device kernel) plus the host
pack (Msg2 equivalent).

Prints exactly ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_QPS = 8.0  # html/faq.html:320

N_DOCS = int(os.environ.get("BENCH_DOCS", "2000"))
N_QUERIES = int(os.environ.get("BENCH_QUERIES", "200"))


def _build_corpus(coll, n_docs: int) -> list[str]:
    """Synthetic zipf-vocabulary corpus; returns the vocabulary."""
    import numpy as np

    from open_source_search_engine_tpu.build import docproc

    rng = np.random.default_rng(42)
    vocab = [f"word{i}" for i in range(2000)]
    varr = np.array(vocab)
    for d in range(n_docs):
        n_words = int(rng.integers(60, 220))
        idx = rng.zipf(1.35, size=n_words) % len(vocab)
        words = varr[idx]
        title = " ".join(words[:4])
        sents = []
        for s in range(0, n_words, 12):
            sents.append(" ".join(words[s:s + 12]) + ".")
        docproc.index_document(
            coll, f"http://site{d % 97}.bench.test/doc{d}",
            f"<html><head><title>{title}</title></head><body><p>"
            + " ".join(sents) + "</p></body></html>")
    return vocab


def _make_queries(vocab: list[str], n: int) -> list[str]:
    import numpy as np

    rng = np.random.default_rng(7)
    qs = []
    for i in range(n):
        n_terms = int(rng.integers(1, 4))  # 1-3 term AND queries
        terms = rng.zipf(1.3, size=n_terms) % len(vocab)
        qs.append(" ".join(vocab[t] for t in terms))
    return qs


BATCH = int(os.environ.get("BENCH_BATCH", "32"))


def main() -> None:
    from open_source_search_engine_tpu.index.collection import Collection
    from open_source_search_engine_tpu.query import engine

    coll = Collection("bench", tempfile.mkdtemp(prefix="osse_bench_"))
    _t0 = time.perf_counter()
    vocab = _build_corpus(coll, N_DOCS)
    build_s = time.perf_counter() - _t0
    queries = _make_queries(vocab, N_QUERIES)
    batches = [queries[i:i + BATCH] for i in range(0, len(queries), BATCH)]

    # warmup: build the resident index + populate the jit cache
    for b in batches:
        engine.search_device_batch(coll, b, topk=10, with_snippets=False)
    for q in queries[:20]:
        engine.search_device(coll, q, topk=10, with_snippets=False)

    # measured: batched resident-index throughput + single-query latency
    t0 = time.perf_counter()
    for b in batches:
        engine.search_device_batch(coll, b, topk=10, with_snippets=False)
    elapsed = time.perf_counter() - t0

    lat0 = time.perf_counter()
    for q in queries[:20]:
        engine.search_device(coll, q, topk=10, with_snippets=False)
    lat_ms = 1000 * (time.perf_counter() - lat0) / 20

    qps = N_QUERIES / elapsed
    print(json.dumps({
        "metric": "queries_per_sec",
        "value": round(qps, 2),
        "unit": "qps",
        "vs_baseline": round(qps / BASELINE_QPS, 2),
    }))
    print(f"# corpus={N_DOCS} docs ({build_s:.1f}s build), "
          f"{N_QUERIES} queries (batch={BATCH}) in {elapsed:.2f}s, "
          f"single-query latency ~{lat_ms:.1f}ms", file=sys.stderr)


if __name__ == "__main__":
    main()
