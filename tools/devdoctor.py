"""devdoctor — backend preflight for the bench/serve planes.

The r05 failure class: a bench run on a TPU host whose backend init
died was silently retried onto the CPU backend, measured, and filed
next to device-measured numbers — the ROADMAP grounding note still
flags r04 as the last device-measured point because of exactly that.
This probe makes the failure loud and machine-readable:

* ``probe()`` initializes the backend with the same bounded
  retry-with-backoff the bench uses, and records platform, device
  kind/count, topology and ``memory_stats()`` (null where the backend
  has none — CPU).
* The verdict distinguishes the cases the harness kept conflating:
  ``ok`` (accelerator up), ``no-accelerator`` (CPU box, CPU run —
  benign), ``fallback`` (a TPU was expected — env says so — but jax
  resolved CPU: the silent-fallback class, now exit 1), and
  ``init-failed`` (backend init raised through every retry).
* ``stamp()`` is the memoized record ``bench._backend_record()``
  merges into EVERY BENCH_* JSON line, so curves spanning runs carry
  the jax version, device kind/count and doctor verdict next to
  ``device_measured``.

CLI: ``python -m tools.devdoctor`` prints the probe JSON and exits
0 (ok), 1 (init-failed / fallback — a TPU host is misbehaving),
2 (no accelerator present — benign on CI boxes).
"""

from __future__ import annotations

import json
import os
import sys
import time

EXIT_OK = 0
EXIT_INIT_FAILED = 1
EXIT_NO_ACCEL = 2

_stamp_cache: dict | None = None


def tpu_expected() -> bool:
    """Does the environment claim a TPU should be reachable? A CPU
    resolution under these signals is the r05 silent-fallback class,
    not a benign CPU run."""
    plat = os.environ.get("JAX_PLATFORMS", "")
    if "tpu" in plat:
        return True
    if plat:  # explicitly forced elsewhere (cpu CI runs land here)
        return False
    if any(k.startswith(("TPU_", "LIBTPU")) for k in os.environ):
        return True
    try:
        import libtpu  # noqa: F401
        return True
    except Exception:
        return False


def probe(max_tries: int = 3) -> dict:
    """Initialize the backend (bounded retry-with-backoff, mirroring
    bench._init_backend) and return the full diagnosis record."""
    base = float(os.environ.get("BENCH_INIT_BACKOFF_S", "5"))
    expected = tpu_expected()
    err: Exception | None = None
    jax = None
    for attempt in range(max_tries):
        try:
            import jax as _jax
            _jax.devices()  # forces backend client init
            jax, err = _jax, None
            break
        except Exception as e:  # noqa: BLE001 — any init failure
            err = e
            try:  # drop the poisoned client so the retry re-inits
                from jax.extend import backend as _jxb
                _jxb.clear_backends()
            except Exception as ce:  # noqa: BLE001
                print(f"# devdoctor: clear_backends failed: {ce!r}",
                      file=sys.stderr)
            if attempt + 1 < max_tries:
                time.sleep(base * (2 ** attempt))
    rec: dict = {"tpu_expected": expected,
                 "error": repr(err)[:300] if err is not None else None}
    if jax is None:
        rec.update({"status": "init-failed", "platform": None,
                    "jax_version": None, "device_kind": None,
                    "device_count": 0, "topology": None,
                    "memory_stats": None})
        return rec
    devs = jax.devices()
    d0 = devs[0]
    try:
        ms = d0.memory_stats()
    except Exception:
        ms = None
    platform = str(jax.default_backend())
    if platform != "cpu":
        status = "ok"
    elif expected:
        status = "fallback"   # the r05 class: TPU host, CPU backend
    else:
        status = "no-accelerator"
    rec.update({
        "status": status,
        "platform": platform,
        "jax_version": jax.__version__,
        "device_kind": str(getattr(d0, "device_kind", "unknown")),
        "device_count": len(devs),
        "topology": {
            "process_count": int(jax.process_count()),
            "devices": [str(d) for d in devs[:16]],
            "coords": [list(getattr(d, "coords", ()) or ())
                       for d in devs[:16]],
        },
        "memory_stats": ({k: int(v) for k, v in ms.items()}
                         if ms else None),
    })
    return rec


def stamp() -> dict:
    """The memoized per-process backend stamp bench merges into every
    BENCH_* JSON line. Keys are chosen not to collide with the
    existing ``backend`` / ``device_measured`` fields."""
    global _stamp_cache
    if _stamp_cache is None:
        rec = probe(max_tries=int(os.environ.get("BENCH_INIT_TRIES",
                                                 "3")))
        _stamp_cache = {
            "doctor": rec["status"],
            "jax_version": rec["jax_version"],
            "device_kind": rec["device_kind"],
            "device_count": rec["device_count"],
            "topology": rec["topology"],
            "memory_stats": rec["memory_stats"],
        }
    return dict(_stamp_cache)


def diagnose(rec: dict) -> str:
    """One actionable paragraph per failure class — what r05 needed
    instead of a silent CPU point."""
    s = rec["status"]
    if s == "ok":
        return (f"backend ok: {rec['platform']} × "
                f"{rec['device_count']} ({rec['device_kind']})")
    if s == "no-accelerator":
        return ("no accelerator present and none expected — CPU "
                "numbers are host-measured, device_measured stays "
                "false")
    if s == "fallback":
        return ("TPU expected (JAX_PLATFORMS/TPU_*/libtpu say so) but "
                "jax resolved the CPU backend — the r05 silent-"
                "fallback class. Check that libtpu matches the jax "
                "version, that no other process holds the TPU "
                "(/dev/accel* busy), and that JAX_PLATFORMS is not "
                "forcing cpu; numbers measured now would be "
                "mislabeled host points.")
    return (f"backend init raised through every retry: {rec['error']} "
            "— check the TPU runtime/tunnel is up (the r05 wedge), "
            "raise BENCH_INIT_BACKOFF_S if the client races runtime "
            "start, or force JAX_PLATFORMS=cpu for an explicit "
            "host-measured run.")


def preflight() -> dict:
    """Bench entry: probe once (shares the stamp cache), print the
    diagnosis to stderr, return the record. Never raises — the legs
    decide what to gate on."""
    try:
        rec = probe(max_tries=int(os.environ.get("BENCH_INIT_TRIES",
                                                 "3")))
    except Exception as e:  # noqa: BLE001 — diagnosis must not wedge
        rec = {"status": "init-failed", "error": repr(e)[:300]}
    print(f"# devdoctor: {diagnose(rec)}", file=sys.stderr)
    return rec


def main() -> int:
    rec = probe(max_tries=int(os.environ.get("BENCH_INIT_TRIES", "3")))
    print(json.dumps(rec, indent=2))
    print(f"# {diagnose(rec)}", file=sys.stderr)
    if rec["status"] in ("init-failed", "fallback"):
        return EXIT_INIT_FAILED
    if rec["status"] == "no-accelerator":
        return EXIT_NO_ACCEL
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
