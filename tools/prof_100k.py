"""Profile F1/F2 kernels against the persistent 100k corpus."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench
import jax


def timed(label, fn, n=3):
    t0 = time.perf_counter()
    out = fn(0)
    jax.block_until_ready(out)
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(1, n + 1):
        out = fn(i)
    jax.block_until_ready(out)
    el = (time.perf_counter() - t0) / n
    print(f"{label}: {1000*el:.0f} ms (first {warm:.1f}s)", flush=True)
    return el


def main():
    from open_source_search_engine_tpu.index.collection import Collection
    from open_source_search_engine_tpu.query import engine
    from open_source_search_engine_tpu.query.compiler import compile_query
    import open_source_search_engine_tpu.query.devindex as dv

    coll = Collection("bench", "/root/bench_corpus")
    t0 = time.perf_counter()
    di = engine.get_device_index(coll)
    print(f"device build: {time.perf_counter()-t0:.0f}s  D_cap={di.D_cap} "
          f"Vc={di.Vc} dense={len(di.dense_slot_of)} cube={len(di.cube_slot_of)}",
          flush=True)

    qs = bench._make_queries(2000, seed=5)
    plans = {}
    f2_cut = min(dv.CUBE_MIN_DF, max(2 * dv.KAPPA_FLOOR, di.n_docs // 8))
    f1_qs, f2_qs = [], []
    for q in qs:
        p = di.plan(compile_query(q, 0))
        if not p.matchable:
            continue
        if p.driver_df > f2_cut:
            f2_qs.append(p)
        else:
            f1_qs.append(p)
    print(f"routing: {len(f1_qs)} f1 / {len(f2_qs)} f2 of {len(qs)}", flush=True)
    k1 = {}
    for p in f1_qs:
        k1.setdefault(di._kappa_of(p, 64), []).append(p)
    print("f1 kappa distribution:", {k: len(v) for k, v in k1.items()}, flush=True)

    # --- F1 batches per kappa rung (warmed, unique plans per iter) ---
    for kappa, ps in sorted(k1.items()):
        if len(ps) < 4 * 32:
            ps = (ps * (4 * 32 // max(len(ps), 1) + 1))
        timed(f"F1 batch32 k={kappa}",
              lambda i, ps=ps, kappa=kappa: di._run_batch(
                  ps[32*i:32*i+32], kappa, min(64, kappa)))

    # --- F2 chunks ---
    bmax = di._f2_bmax()
    print(f"f2 bmax={bmax}", flush=True)
    if f2_qs:
        ps = f2_qs * (4 * bmax // max(len(f2_qs), 1) + 1)
        timed(f"F2 chunk B={bmax}",
              lambda i, ps=ps: di._run_batch_f2(ps[bmax*i:bmax*i+bmax], 64,
                                                exact=False))
        timed("F2 chunk B=4",
              lambda i, ps=ps: di._run_batch_f2(ps[4*i:4*i+4], 64,
                                                exact=False))

    # --- end-to-end search_batch ---
    timed("search_batch 32 (raw)", lambda i: [
        np.concatenate([r[1] for r in di.search_batch(qs[800+32*i:832+32*i],
                                                      topk=64)])], n=3)


if __name__ == "__main__":
    main()
