"""osselint — the project's AST invariant linter.

Every rule here encodes a bug class this repo has actually shipped (or a
reference-engine discipline that keeps it from shipping one):

* ``ttlcache-offplane`` — PR 4 unified caching onto the cache plane
  (generation invalidation + single-flight); a raw ``TtlCache(`` off the
  plane silently serves stale entries across index generations.
* ``urllib-in-parallel`` — all cross-shard HTTP rides the pooled
  ``parallel/transport.py`` (hedging, tracing, connection reuse); a bare
  ``urlopen`` bypasses every one of those.
* ``bare-stats-timed`` — the query path must use ``trace.timed_span``
  (which also feeds g_stats) so cross-shard waterfalls stay complete; a
  bare ``g_stats.timed`` records a duration no trace can attribute.
* ``id-key`` — PR 4 shipped an ``id(conf)`` cache key: CPython reuses
  addresses after GC, so a dead object's id aliases a live one and the
  cache returns wrong-config results. ``id()`` never belongs in a key.
* ``blocking-under-lock`` — sleeping or doing socket/subprocess I/O
  inside a ``with <lock>:`` body stalls every thread behind the lock.
* ``silent-except`` — ``except: pass`` ate real corruption reports more
  than once; failures must at least count or log.
* ``mutable-default`` — the classic shared-default-argument aliasing.
* ``thread-spawn`` — threads come from ``utils.threads`` so every one is
  a *named daemon*: names make lockcheck/profiler output readable and
  daemonization keeps test runs from hanging on shutdown.
* ``locked-global`` — module-level mutable state in ``serve/`` and
  ``parallel/`` is shared across request threads; mutations outside a
  ``with <lock>:`` are data races.
* ``device-sync`` — ``jax.device_get``/``block_until_ready`` force a
  host sync; outside the two blessed device-boundary modules they
  silently serialize the TPU pipeline.

Waive a finding with a trailing comment on its line::

    risky_call()  # osselint: ignore[rule-name] — why it is safe here

``python -m tools.osselint`` scans the package + tools + tests;
``--changed`` scans only files touched vs. git HEAD; ``--format=json``
emits machine-readable findings. Exit status 1 when anything unwaived
is found.
"""

from __future__ import annotations

import ast
import json
import re
import sys
from dataclasses import dataclass
from pathlib import Path

PKG = "open_source_search_engine_tpu"

#: dirs never scanned (fixtures are deliberate violations)
EXCLUDE_PARTS = {"__pycache__", "lint_fixtures", ".git"}

_WAIVER_RE = re.compile(r"osselint:\s*ignore\[([A-Za-z0-9_\-,\s]+)\]")

#: a ``# osselint: path=<relpath>`` comment in the first lines of a
#: file re-scopes it to that virtual path (fixtures exercise
#: parallel/-only rules from tests/lint_fixtures/)
_PATH_PRAGMA_RE = re.compile(r"osselint:\s*path=(\S+)")

#: ``with`` context expressions whose final identifier matches this are
#: treated as lock acquisitions by blocking-under-lock / locked-global
_LOCKISH_RE = re.compile(r"lock|mutex|cond|(^|_)cv$", re.IGNORECASE)

#: dotted-call prefixes that block the calling thread
_BLOCKING_PREFIXES = ("socket.", "urllib.", "subprocess.")
_BLOCKING_EXACT = {"time.sleep", "sleep"}

#: mutating container methods for locked-global
_MUTATORS = {"append", "add", "update", "pop", "popitem", "clear",
             "extend", "remove", "discard", "setdefault", "insert"}

#: cache-ish methods whose key args must not contain id()
_CACHE_METHODS = {"get", "put", "setdefault", "get_or_compute"}


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    msg: str

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line,
                "rule": self.rule, "msg": self.msg}


class Ctx:
    """One parsed file: tree + parent links + per-line waivers."""

    def __init__(self, src: str, rel: str):
        self.rel = rel.replace("\\", "/")
        self.tree = ast.parse(src)
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.waivers: dict[int, set[str]] = {}
        for i, line in enumerate(src.splitlines(), start=1):
            m = _WAIVER_RE.search(line)
            if m:
                self.waivers[i] = {r.strip() for r in
                                   m.group(1).split(",") if r.strip()}

    def ancestors(self, node: ast.AST):
        """(child, parent) pairs walking from ``node`` to the root."""
        cur = node
        while True:
            parent = self.parents.get(cur)
            if parent is None:
                return
            yield cur, parent
            cur = parent


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _final_ident(node: ast.AST) -> str | None:
    """Last identifier of an expression (``self._lock`` → ``_lock``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return _final_ident(node.func)
    return None


def _is_lockish(expr: ast.AST) -> bool:
    ident = _final_ident(expr)
    return ident is not None and bool(_LOCKISH_RE.search(ident))


def _under_lock(ctx: Ctx, node: ast.AST) -> bool:
    """Is ``node`` lexically inside a ``with <lock>:`` body?"""
    for _child, parent in ctx.ancestors(node):
        if isinstance(parent, (ast.With, ast.AsyncWith)):
            if any(_is_lockish(item.context_expr)
                   for item in parent.items):
                return True
    return False


def _body_calls(body: list[ast.stmt]):
    """Every Call lexically in ``body``, NOT descending into nested
    function/lambda definitions (closures run later, not here)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# rules: each is (name, path-predicate, checker(ctx) -> [Finding])
# ---------------------------------------------------------------------------

def _in_pkg(rel: str) -> bool:
    return rel.startswith(PKG + "/")


def _scope_pkg_tools(rel: str) -> bool:
    return _in_pkg(rel) or rel.startswith("tools/")


def rule_ttlcache_offplane(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name and name.split(".")[-1] == "TtlCache":
                out.append(Finding(
                    ctx.rel, node.lineno, "ttlcache-offplane",
                    "raw TtlCache() off the cache plane — use "
                    "cache.plane (generation invalidation, "
                    "single-flight)"))
    return out


def _ttl_scope(rel: str) -> bool:
    return _in_pkg(rel) and rel not in (
        f"{PKG}/cache/plane.py", f"{PKG}/utils/ttlcache.py")


def rule_urllib_in_parallel(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        bad = None
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "urllib" for a in node.names):
                bad = "import urllib"
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] == "urllib":
                bad = f"from {node.module} import ..."
        elif isinstance(node, ast.Call):
            name = dotted(node.func)
            if name and name.split(".")[-1] == "urlopen":
                bad = "urlopen()"
        if bad:
            out.append(Finding(
                ctx.rel, node.lineno, "urllib-in-parallel",
                f"{bad} in parallel/ — all cross-shard HTTP goes "
                "through transport.py (pooling, hedging, tracing)"))
    return out


def _urllib_scope(rel: str) -> bool:
    return (rel.startswith(f"{PKG}/parallel/")
            and not rel.endswith("/transport.py"))


def rule_bare_stats_timed(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and dotted(node.func) == "g_stats.timed":
            out.append(Finding(
                ctx.rel, node.lineno, "bare-stats-timed",
                "bare g_stats.timed() on the query path — use "
                "trace.timed_span (feeds stats AND the waterfall)"))
    return out


def _timed_scope(rel: str) -> bool:
    return any(rel.startswith(f"{PKG}/{d}/")
               for d in ("query", "parallel", "serve"))


def rule_id_key(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "id"):
            continue
        keyish = False
        for child, parent in ctx.ancestors(node):
            if isinstance(parent, ast.Tuple):
                keyish = True
            elif isinstance(parent, ast.Dict) and child in parent.keys:
                keyish = True
            elif isinstance(parent, ast.Subscript) \
                    and child is parent.slice:
                keyish = True
            elif isinstance(parent, ast.Call) and child is not parent.func:
                ident = _final_ident(parent.func)
                if ident in _CACHE_METHODS:
                    keyish = True
            if keyish:
                break
        if keyish:
            out.append(Finding(
                ctx.rel, node.lineno, "id-key",
                "id() in a cache/dict key — CPython reuses addresses "
                "after GC, so dead objects alias live ones (the PR 4 "
                "id(conf) bug); key on identity-stable values"))
    return out


def rule_blocking_under_lock(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any(_is_lockish(item.context_expr)
                   for item in node.items):
            continue
        for call in _body_calls(node.body):
            name = dotted(call.func)
            if name is None:
                continue
            if name in _BLOCKING_EXACT \
                    or name.startswith(_BLOCKING_PREFIXES):
                out.append(Finding(
                    ctx.rel, call.lineno, "blocking-under-lock",
                    f"{name}() inside a `with lock:` body — every "
                    "thread behind the lock stalls for the call"))
    return out


def rule_silent_except(ctx: Ctx) -> list[Finding]:
    out = []

    def broad(t: ast.AST | None) -> bool:
        if t is None:
            return True
        if isinstance(t, ast.Name):
            return t.id in ("Exception", "BaseException")
        if isinstance(t, ast.Tuple):
            return any(broad(e) for e in t.elts)
        return False

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(Finding(
                ctx.rel, node.lineno, "silent-except",
                "bare `except:` — catches KeyboardInterrupt/SystemExit "
                "too; name the exception"))
        elif broad(node.type) and len(node.body) == 1 \
                and isinstance(node.body[0], ast.Pass):
            out.append(Finding(
                ctx.rel, node.lineno, "silent-except",
                "`except Exception: pass` — failures must at least "
                "count (g_stats) or log"))
    return out


def rule_mutable_default(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None]
        for d in defaults:
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and isinstance(d.func, ast.Name)
                and d.func.id in ("list", "dict", "set"))
            if mutable:
                out.append(Finding(
                    ctx.rel, d.lineno, "mutable-default",
                    "mutable default argument — shared across every "
                    "call; default to None and create inside"))
    return out


def rule_thread_spawn(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name and (name == "Thread"
                         or name.endswith(".Thread")):
                out.append(Finding(
                    ctx.rel, node.lineno, "thread-spawn",
                    "raw threading.Thread — use utils.threads.spawn/"
                    "make_thread (named daemon threads; lockcheck and "
                    "the profiler need the names)"))
    return out


def _thread_scope(rel: str) -> bool:
    return _in_pkg(rel) and rel != f"{PKG}/utils/threads.py"


def rule_locked_global(ctx: Ctx) -> list[Finding]:
    mutables: set[str] = set()
    for stmt in ctx.tree.body:
        targets: list[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        is_mut = isinstance(value, (ast.Dict, ast.List, ast.Set,
                                    ast.ListComp, ast.DictComp,
                                    ast.SetComp)) or (
            isinstance(value, ast.Call)
            and _final_ident(value.func) in ("dict", "list", "set",
                                             "defaultdict",
                                             "OrderedDict", "deque",
                                             "Counter"))
        if not is_mut:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                mutables.add(t.id)
    if not mutables:
        return []

    out = []

    def in_function(node: ast.AST) -> bool:
        return any(isinstance(p, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))
                   for _c, p in ctx.ancestors(node))

    def flag(node: ast.AST, name: str) -> None:
        if in_function(node) and not _under_lock(ctx, node):
            out.append(Finding(
                ctx.rel, node.lineno, "locked-global",
                f"module-level mutable `{name}` mutated outside a "
                "`with lock:` — request threads share it"))

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = node.targets if isinstance(node, (ast.Assign,
                                                        ast.Delete)) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in mutables:
                    flag(node, t.value.id)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in mutables \
                and node.func.attr in _MUTATORS:
            flag(node, node.func.value.id)
    return out


def _locked_global_scope(rel: str) -> bool:
    return rel.startswith((f"{PKG}/serve/", f"{PKG}/parallel/"))


def rule_device_sync(ctx: Ctx) -> list[Finding]:
    # the resident serving loop is the one file where even ASYNC
    # host→device traffic is banned: submit() runs on request threads
    # and the loop's contract is "enqueue only" — staging transfers
    # belong in devindex.py's issue path
    resident = ctx.rel == f"{PKG}/query/resident.py"
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        tail = name.split(".")[-1] if name else ""
        hit = None
        if tail == "device_get":
            hit = "device_get"
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "block_until_ready":
            hit = "block_until_ready"
        elif resident and tail in ("device_put", "asarray"):
            out.append(Finding(
                ctx.rel, node.lineno, "device-sync",
                f"{tail} in the resident loop — the enqueue path must "
                "not stage device buffers; issue_batch in "
                "query/devindex.py owns host→device transfers"))
            continue
        if hit:
            out.append(Finding(
                ctx.rel, node.lineno, "device-sync",
                f"{hit} outside the device boundary — host syncs "
                "serialize the TPU pipeline; keep them in "
                "query/devindex.py or query/scorer.py"))
    return out


def _device_scope(rel: str) -> bool:
    return _in_pkg(rel) and rel not in (
        f"{PKG}/query/devindex.py", f"{PKG}/query/scorer.py")


#: (rule-name, path predicate, checker)
RULES = [
    ("ttlcache-offplane", _ttl_scope, rule_ttlcache_offplane),
    ("urllib-in-parallel", _urllib_scope, rule_urllib_in_parallel),
    ("bare-stats-timed", _timed_scope, rule_bare_stats_timed),
    ("id-key", _in_pkg, rule_id_key),
    ("blocking-under-lock", _in_pkg, rule_blocking_under_lock),
    ("silent-except", _scope_pkg_tools, rule_silent_except),
    ("mutable-default", _scope_pkg_tools, rule_mutable_default),
    ("thread-spawn", _thread_scope, rule_thread_spawn),
    ("locked-global", _locked_global_scope, rule_locked_global),
    ("device-sync", _device_scope, rule_device_sync),
]

RULE_NAMES = {name for name, _p, _c in RULES}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def check_source(src: str, rel: str) -> list[Finding]:
    """Lint one source text as if it lived at ``rel`` (posix relative
    path — rule scoping keys off it). The fixture/test entry point."""
    rel = rel.replace("\\", "/")
    for line in src.splitlines()[:5]:
        m = _PATH_PRAGMA_RE.search(line)
        if m:
            rel = m.group(1)
            break
    try:
        ctx = Ctx(src, rel)
    except SyntaxError as exc:
        return [Finding(rel, exc.lineno or 1, "syntax-error", str(exc))]
    findings: list[Finding] = []
    for name, pred, checker in RULES:
        if not pred(rel):
            continue
        for f in checker(ctx):
            if name in ctx.waivers.get(f.line, ()):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings


def default_paths(root: Path) -> list[Path]:
    return [root / PKG, root / "tools", root / "tests"]


def iter_py_files(paths: list[Path], root: Path) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not EXCLUDE_PARTS & set(f.relative_to(root).parts):
                    out.append(f)
    return out


def changed_files(root: Path) -> list[Path]:
    """Files touched vs. HEAD: unstaged + staged + untracked."""
    import subprocess
    names: set[str] = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "diff", "--name-only", "--cached"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(args, cwd=root, capture_output=True,
                              text=True, check=False)
        names.update(line.strip() for line in proc.stdout.splitlines()
                     if line.strip())
    out = []
    for n in sorted(names):
        p = root / n
        if p.suffix == ".py" and p.exists() \
                and not (EXCLUDE_PARTS & set(Path(n).parts)):
            out.append(p)
    return out


def lint_files(files: list[Path], root: Path) -> list[Finding]:
    findings: list[Finding] = []
    for f in files:
        rel = f.relative_to(root).as_posix()
        try:
            src = f.read_text(encoding="utf-8")
        except OSError as exc:
            findings.append(Finding(rel, 1, "unreadable", str(exc)))
            continue
        findings.extend(check_source(src, rel))
    return findings


def main(argv: list[str] | None = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="osselint", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: package + "
                         "tools + tests)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files changed vs. git HEAD")
    ap.add_argument("--root", default=None,
                    help="repo root (default: this file's repo)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, _pred, checker in RULES:
            doc = (checker.__doc__ or "").strip().splitlines()
            print(f"{name}: {doc[0] if doc else ''}")
        return 0

    root = Path(args.root).resolve() if args.root \
        else Path(__file__).resolve().parent.parent
    if args.changed:
        files = changed_files(root)
    elif args.paths:
        files = iter_py_files([Path(p).resolve() for p in args.paths],
                              root)
    else:
        files = iter_py_files(default_paths(root), root)

    findings = lint_files(files, root)
    if args.format == "json":
        print(json.dumps({"files": len(files),
                          "findings": [f.as_dict() for f in findings]},
                         indent=2))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}: {f.rule}: {f.msg}")
        print(f"osselint: {len(files)} files, "
              f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
