"""osselint — the project's AST invariant linter.

Every rule here encodes a bug class this repo has actually shipped (or a
reference-engine discipline that keeps it from shipping one):

* ``ttlcache-offplane`` — PR 4 unified caching onto the cache plane
  (generation invalidation + single-flight); a raw ``TtlCache(`` off the
  plane silently serves stale entries across index generations.
* ``urllib-in-parallel`` — all cross-shard HTTP rides the pooled
  ``parallel/transport.py`` (hedging, tracing, connection reuse); a bare
  ``urlopen`` bypasses every one of those.
* ``bare-stats-timed`` — the query path must use ``trace.timed_span``
  (which also feeds g_stats) so cross-shard waterfalls stay complete; a
  bare ``g_stats.timed`` records a duration no trace can attribute.
* ``id-key`` — PR 4 shipped an ``id(conf)`` cache key: CPython reuses
  addresses after GC, so a dead object's id aliases a live one and the
  cache returns wrong-config results. ``id()`` never belongs in a key.
* ``blocking-under-lock`` — sleeping or doing socket/subprocess I/O
  inside a ``with <lock>:`` body stalls every thread behind the lock.
* ``silent-except`` — ``except: pass`` ate real corruption reports more
  than once; failures must at least count or log.
* ``mutable-default`` — the classic shared-default-argument aliasing.
* ``thread-spawn`` — threads come from ``utils.threads`` so every one is
  a *named daemon*: names make lockcheck/profiler output readable and
  daemonization keeps test runs from hanging on shutdown.
* ``locked-global`` — module-level mutable state in ``serve/`` and
  ``parallel/`` is shared across request threads; mutations outside a
  ``with <lock>:`` are data races.
* ``device-sync`` — ``jax.device_get``/``block_until_ready`` force a
  host sync; outside the two blessed device-boundary modules they
  silently serialize the TPU pipeline.
* ``proc-spawn`` — child processes and signals are the fleet plane's
  job: ``subprocess.Popen`` / ``os.kill`` / ``os.fork`` outside
  ``parallel/fleet.py`` and ``utils/chaos.py`` spawn or kill processes
  no supervisor tracks and no teardown reaps — exactly the orphan
  leaks the FleetManager process groups exist to prevent.
* ``residency-bypass`` — HBM-resident state is the tenancy plane's
  job: a ``DeviceIndex(`` / ``ResidentLoop(`` constructed outside
  ``serve/tenancy.py`` and the ``query/engine.py`` factories creates
  device buffers the ResidencyManager never sees — the LRU can't
  evict them, the membudget 'device' label never bills them, and
  delColl can't unserve them.

The ``jit-*`` family covers JAX trace discipline — the failure modes
are invisible until they show up as a latency cliff (the Gigablast
analog: Msg39 latency spikes when a query shape misses every warm
plan):

* ``jit-unstable-static`` — a float / container / array /
  unbucketed ``len()``-derived value passed to a ``static_argnames``
  parameter: every distinct value is a fresh XLA compile (retrace
  cliff + unbounded jit cache).
* ``jit-in-body`` — ``jax.jit(...)`` wrapped inside a function body:
  each call mints a fresh wrapper with an empty compile cache, so
  nothing is ever warm (memoized factories via ``lru_cache`` are the
  sanctioned escape).
* ``jit-mutable-closure`` — a jitted function reading module-level
  mutable state: the value is frozen into the traced program at
  compile time and silently goes stale when the dict/list mutates.
* ``jit-donated-reuse`` — an argument donated via ``donate_argnums``
  read after the donating call: donation deallocates the buffer; the
  read returns garbage (or crashes) on real backends.
* ``jit-implicit-transfer`` — ``float()`` / ``.item()`` /
  ``np.asarray()`` / ``.tolist()`` on a device value outside the
  device-boundary modules (devindex, scorer, sharded): an implicit
  device→host sync on the request path, exactly the hidden
  serialization the resident loop exists to avoid.
* ``bare-deadline`` — raw ``time.monotonic() + timeout`` /
  ``x - time.monotonic()`` deadline math on the query/parallel/serve
  paths: a hand-rolled deadline never stamps ``X-OSSE-Deadline`` onto
  scatter legs and never feeds the ``deadline.abandoned`` counters —
  use ``utils.deadline.Deadline`` (``.after``/``.remaining``/
  ``.clamp``). ``now - t0`` duration measurement stays legal.
* ``adhoc-timing`` — ``time.perf_counter() - t0`` /
  ``time.time() - t0`` latency measurement on the query/parallel/serve
  paths: the measured duration reaches neither the /admin/perf
  histograms nor the trace waterfall (two-timing-planes drift) — use
  ``trace.timed_span`` or ``trace.record``, which feed both.
  ``time.monotonic() - t0`` budget arithmetic stays legal.

Waive a finding with a trailing comment on its line::

    risky_call()  # osselint: ignore[rule-name] — why it is safe here

``python -m tools.osselint`` scans the package + tools + tests;
``--changed`` scans only files touched vs. git HEAD; ``--format=json``
emits machine-readable findings. Exit status 1 when anything unwaived
is found.
"""

from __future__ import annotations

import ast
import json
import re
import sys
from dataclasses import dataclass
from pathlib import Path

PKG = "open_source_search_engine_tpu"

#: dirs never scanned (fixtures are deliberate violations)
EXCLUDE_PARTS = {"__pycache__", "lint_fixtures", ".git"}

_WAIVER_RE = re.compile(r"osselint:\s*ignore\[([A-Za-z0-9_\-,\s]+)\]")

#: a ``# osselint: path=<relpath>`` comment in the first lines of a
#: file re-scopes it to that virtual path (fixtures exercise
#: parallel/-only rules from tests/lint_fixtures/)
_PATH_PRAGMA_RE = re.compile(r"osselint:\s*path=(\S+)")

#: ``with`` context expressions whose final identifier matches this are
#: treated as lock acquisitions by blocking-under-lock / locked-global
_LOCKISH_RE = re.compile(r"lock|mutex|cond|(^|_)cv$", re.IGNORECASE)

#: dotted-call prefixes that block the calling thread
_BLOCKING_PREFIXES = ("socket.", "urllib.", "subprocess.")
_BLOCKING_EXACT = {"time.sleep", "sleep"}

#: mutating container methods for locked-global
_MUTATORS = {"append", "add", "update", "pop", "popitem", "clear",
             "extend", "remove", "discard", "setdefault", "insert"}

#: cache-ish methods whose key args must not contain id()
_CACHE_METHODS = {"get", "put", "setdefault", "get_or_compute"}


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    msg: str

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line,
                "rule": self.rule, "msg": self.msg}


class Ctx:
    """One parsed file: tree + parent links + per-line waivers."""

    def __init__(self, src: str, rel: str):
        self.rel = rel.replace("\\", "/")
        self.tree = ast.parse(src)
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.waivers: dict[int, set[str]] = {}
        for i, line in enumerate(src.splitlines(), start=1):
            m = _WAIVER_RE.search(line)
            if m:
                self.waivers[i] = {r.strip() for r in
                                   m.group(1).split(",") if r.strip()}

    def ancestors(self, node: ast.AST):
        """(child, parent) pairs walking from ``node`` to the root."""
        cur = node
        while True:
            parent = self.parents.get(cur)
            if parent is None:
                return
            yield cur, parent
            cur = parent


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _final_ident(node: ast.AST) -> str | None:
    """Last identifier of an expression (``self._lock`` → ``_lock``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return _final_ident(node.func)
    return None


def _is_lockish(expr: ast.AST) -> bool:
    ident = _final_ident(expr)
    return ident is not None and bool(_LOCKISH_RE.search(ident))


def _under_lock(ctx: Ctx, node: ast.AST) -> bool:
    """Is ``node`` lexically inside a ``with <lock>:`` body?"""
    for _child, parent in ctx.ancestors(node):
        if isinstance(parent, (ast.With, ast.AsyncWith)):
            if any(_is_lockish(item.context_expr)
                   for item in parent.items):
                return True
    return False


def _body_calls(body: list[ast.stmt]):
    """Every Call lexically in ``body``, NOT descending into nested
    function/lambda definitions (closures run later, not here)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# rules: each is (name, path-predicate, checker(ctx) -> [Finding])
# ---------------------------------------------------------------------------

def _in_pkg(rel: str) -> bool:
    return rel.startswith(PKG + "/")


def _scope_pkg_tools(rel: str) -> bool:
    return _in_pkg(rel) or rel.startswith("tools/")


def rule_ttlcache_offplane(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name and name.split(".")[-1] == "TtlCache":
                out.append(Finding(
                    ctx.rel, node.lineno, "ttlcache-offplane",
                    "raw TtlCache() off the cache plane — use "
                    "cache.plane (generation invalidation, "
                    "single-flight)"))
    return out


def _ttl_scope(rel: str) -> bool:
    return _in_pkg(rel) and rel not in (
        f"{PKG}/cache/plane.py", f"{PKG}/utils/ttlcache.py")


def rule_urllib_in_parallel(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        bad = None
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "urllib" for a in node.names):
                bad = "import urllib"
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] == "urllib":
                bad = f"from {node.module} import ..."
        elif isinstance(node, ast.Call):
            name = dotted(node.func)
            if name and name.split(".")[-1] == "urlopen":
                bad = "urlopen()"
        if bad:
            out.append(Finding(
                ctx.rel, node.lineno, "urllib-in-parallel",
                f"{bad} in parallel/ — all cross-shard HTTP goes "
                "through transport.py (pooling, hedging, tracing)"))
    return out


def _urllib_scope(rel: str) -> bool:
    return (rel.startswith(f"{PKG}/parallel/")
            and not rel.endswith("/transport.py"))


def rule_bare_stats_timed(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and dotted(node.func) == "g_stats.timed":
            out.append(Finding(
                ctx.rel, node.lineno, "bare-stats-timed",
                "bare g_stats.timed() on the query path — use "
                "trace.timed_span (feeds stats AND the waterfall)"))
    return out


def _timed_scope(rel: str) -> bool:
    return any(rel.startswith(f"{PKG}/{d}/")
               for d in ("query", "parallel", "serve"))


#: stats/trace entry points whose first positional argument is a
#: metric name — a dynamically built name there mints a new time
#: series per distinct value (the devindex.wave_f1+f2_n5 class:
#: one gauge per observed wave count, unbounded dashboards).
_STATS_NAME_FUNCS = {
    "g_stats.count", "g_stats.gauge", "g_stats.record_ms",
    "g_stats.timed", "trace.record", "trace.timed_span",
    "trace_mod.record", "trace_mod.timed_span",
}


def rule_stats_cardinality(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and dotted(node.func) in _STATS_NAME_FUNCS
                and node.args):
            continue
        arg = node.args[0]
        dyn = None
        if isinstance(arg, ast.JoinedStr) and any(
                isinstance(v, ast.FormattedValue) for v in arg.values):
            dyn = "an f-string"
        elif isinstance(arg, ast.Call) \
                and isinstance(arg.func, ast.Attribute) \
                and arg.func.attr == "format":
            dyn = ".format()"
        elif isinstance(arg, ast.BinOp) \
                and isinstance(arg.op, ast.Mod):
            dyn = "%-formatting"
        elif isinstance(arg, ast.BinOp) \
                and isinstance(arg.op, ast.Add):
            dyn = "concatenation"
        if dyn:
            out.append(Finding(
                ctx.rel, node.lineno, "stats-cardinality",
                f"stat name built with {dyn} — every distinct value "
                "mints a new time series (unbounded cardinality); "
                "bucket the variable and look the name up from a "
                "module-level literal table"))
    return out


def _stats_name_scope(rel: str) -> bool:
    return rel.startswith(f"{PKG}/query/")


def rule_id_key(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "id"):
            continue
        keyish = False
        for child, parent in ctx.ancestors(node):
            if isinstance(parent, ast.Tuple):
                keyish = True
            elif isinstance(parent, ast.Dict) and child in parent.keys:
                keyish = True
            elif isinstance(parent, ast.Subscript) \
                    and child is parent.slice:
                keyish = True
            elif isinstance(parent, ast.Call) and child is not parent.func:
                ident = _final_ident(parent.func)
                if ident in _CACHE_METHODS:
                    keyish = True
            if keyish:
                break
        if keyish:
            out.append(Finding(
                ctx.rel, node.lineno, "id-key",
                "id() in a cache/dict key — CPython reuses addresses "
                "after GC, so dead objects alias live ones (the PR 4 "
                "id(conf) bug); key on identity-stable values"))
    return out


def rule_blocking_under_lock(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any(_is_lockish(item.context_expr)
                   for item in node.items):
            continue
        for call in _body_calls(node.body):
            name = dotted(call.func)
            if name is None:
                continue
            if name in _BLOCKING_EXACT \
                    or name.startswith(_BLOCKING_PREFIXES):
                out.append(Finding(
                    ctx.rel, call.lineno, "blocking-under-lock",
                    f"{name}() inside a `with lock:` body — every "
                    "thread behind the lock stalls for the call"))
    return out


def rule_silent_except(ctx: Ctx) -> list[Finding]:
    out = []

    def broad(t: ast.AST | None) -> bool:
        if t is None:
            return True
        if isinstance(t, ast.Name):
            return t.id in ("Exception", "BaseException")
        if isinstance(t, ast.Tuple):
            return any(broad(e) for e in t.elts)
        return False

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(Finding(
                ctx.rel, node.lineno, "silent-except",
                "bare `except:` — catches KeyboardInterrupt/SystemExit "
                "too; name the exception"))
        elif broad(node.type) and len(node.body) == 1 \
                and isinstance(node.body[0], ast.Pass):
            out.append(Finding(
                ctx.rel, node.lineno, "silent-except",
                "`except Exception: pass` — failures must at least "
                "count (g_stats) or log"))
    return out


def rule_mutable_default(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None]
        for d in defaults:
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and isinstance(d.func, ast.Name)
                and d.func.id in ("list", "dict", "set"))
            if mutable:
                out.append(Finding(
                    ctx.rel, d.lineno, "mutable-default",
                    "mutable default argument — shared across every "
                    "call; default to None and create inside"))
    return out


def rule_thread_spawn(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name and (name == "Thread"
                         or name.endswith(".Thread")):
                out.append(Finding(
                    ctx.rel, node.lineno, "thread-spawn",
                    "raw threading.Thread — use utils.threads.spawn/"
                    "make_thread (named daemon threads; lockcheck and "
                    "the profiler need the names)"))
    return out


def _thread_scope(rel: str) -> bool:
    return _in_pkg(rel) and rel != f"{PKG}/utils/threads.py"


#: signal/fork primitives that create or destroy processes behind the
#: fleet plane's back (``proc.kill()``/``send_signal()`` methods on a
#: Popen handle stay legal — they act on a handle someone owns)
_PROC_CALLS = {"os.kill", "os.killpg", "os.fork", "os.forkpty"}


def rule_proc_spawn(ctx: Ctx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if not name:
            continue
        if name == "Popen" or name.endswith(".Popen"):
            what = "subprocess.Popen"
        elif name in _PROC_CALLS:
            what = name
        else:
            continue
        out.append(Finding(
            ctx.rel, node.lineno, "proc-spawn",
            f"{what} outside the fleet plane — child processes and "
            "signals belong to parallel/fleet.py (supervised, "
            "process-grouped, reaped at teardown) or utils/chaos.py "
            "(aimed faults); a stray spawn/kill leaks orphans no "
            "teardown reaps"))
    return out


def _proc_scope(rel: str) -> bool:
    """Package + tests, minus the two modules whose job this is.
    tools/ is out of scope by construction — build/ops scripts run
    outside the serving tree."""
    if rel in (f"{PKG}/parallel/fleet.py", f"{PKG}/utils/chaos.py"):
        return False
    return rel.startswith((f"{PKG}/", "tests/"))


#: the classes whose construction mints HBM-resident state
_RESIDENCY_CLASSES = {"DeviceIndex", "ResidentLoop"}


def rule_residency_bypass(ctx: Ctx) -> list[Finding]:
    """DeviceIndex/ResidentLoop constructed outside the residency
    plane — device buffers the ResidencyManager never tracks: the
    tenant LRU can't evict them under membudget pressure, the
    'device' label never bills them, and delColl can't unserve
    them. Go through query/engine's factories
    (``build_device_index`` / ``spawn_resident_loop`` /
    ``get_resident_loop``), which serve/tenancy.py owns."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        tail = name.split(".")[-1] if name else ""
        if tail in _RESIDENCY_CLASSES:
            out.append(Finding(
                ctx.rel, node.lineno, "residency-bypass",
                f"{tail}() outside the residency plane — buffers the "
                "ResidencyManager can't evict, bill, or unserve; use "
                "query/engine's build_device_index / "
                "spawn_resident_loop / get_resident_loop (owned by "
                "serve/tenancy.py)"))
    return out


def _residency_scope(rel: str) -> bool:
    """Package only, minus the residency plane and the engine
    factories. Tests stay out of scope — they construct ResidentLoop
    directly against fakes."""
    return _in_pkg(rel) and rel not in (
        f"{PKG}/serve/tenancy.py", f"{PKG}/query/engine.py")


def _module_mutables(tree: ast.Module) -> set[str]:
    """Module-level names bound to mutable containers."""
    mutables: set[str] = set()
    for stmt in tree.body:
        targets: list[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        is_mut = isinstance(value, (ast.Dict, ast.List, ast.Set,
                                    ast.ListComp, ast.DictComp,
                                    ast.SetComp)) or (
            isinstance(value, ast.Call)
            and _final_ident(value.func) in ("dict", "list", "set",
                                             "defaultdict",
                                             "OrderedDict", "deque",
                                             "Counter"))
        if not is_mut:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                mutables.add(t.id)
    return mutables


def rule_locked_global(ctx: Ctx) -> list[Finding]:
    mutables = _module_mutables(ctx.tree)
    if not mutables:
        return []

    out = []

    def in_function(node: ast.AST) -> bool:
        return any(isinstance(p, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))
                   for _c, p in ctx.ancestors(node))

    def flag(node: ast.AST, name: str) -> None:
        if in_function(node) and not _under_lock(ctx, node):
            out.append(Finding(
                ctx.rel, node.lineno, "locked-global",
                f"module-level mutable `{name}` mutated outside a "
                "`with lock:` — request threads share it"))

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = node.targets if isinstance(node, (ast.Assign,
                                                        ast.Delete)) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in mutables:
                    flag(node, t.value.id)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in mutables \
                and node.func.attr in _MUTATORS:
            flag(node, node.func.value.id)
    return out


def _locked_global_scope(rel: str) -> bool:
    return rel.startswith((f"{PKG}/serve/", f"{PKG}/parallel/"))


def rule_device_sync(ctx: Ctx) -> list[Finding]:
    # the resident serving loop is the one file where even ASYNC
    # host→device traffic is banned: submit() runs on request threads
    # and the loop's contract is "enqueue only" — staging transfers
    # belong in devindex.py's issue path
    resident = ctx.rel == f"{PKG}/query/resident.py"
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        tail = name.split(".")[-1] if name else ""
        hit = None
        if tail == "device_get":
            hit = "device_get"
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "block_until_ready":
            hit = "block_until_ready"
        elif resident and tail in ("device_put", "asarray"):
            out.append(Finding(
                ctx.rel, node.lineno, "device-sync",
                f"{tail} in the resident loop — the enqueue path must "
                "not stage device buffers; issue_batch in "
                "query/devindex.py owns host→device transfers"))
            continue
        if hit:
            out.append(Finding(
                ctx.rel, node.lineno, "device-sync",
                f"{hit} outside the device boundary — host syncs "
                "serialize the TPU pipeline; keep them in "
                "query/devindex.py or query/scorer.py"))
    return out


def _device_scope(rel: str) -> bool:
    return _in_pkg(rel) and rel not in (
        f"{PKG}/query/devindex.py", f"{PKG}/query/scorer.py",
        f"{PKG}/build/devbuild.py")


def _devbuild_scope(rel: str) -> bool:
    return rel == f"{PKG}/build/devbuild.py"


#: the numpy orderings whose presence means a posting stage fell back
#: to the host (each has a jnp twin the ingest plane must use instead)
_HOST_SORTS = {"sort", "unique", "argsort", "lexsort"}


def rule_host_sort(ctx: Ctx) -> list[Finding]:
    """``build/devbuild.py`` is the device ingest plane: the posting
    sort/dedup/pack pipeline stays on-chip by contract (mirroring the
    device-sync fence on ``query/resident.py``). A ``np.sort`` /
    ``np.unique`` / ``np.argsort`` / ``sorted`` call there means a
    stage quietly fell back to host ordering — exactly the O(corpus)
    CPU work the plane exists to remove. Host ordering belongs to the
    oracle pipeline in ``query/devindex.py``."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if not name:
            continue
        parts = name.split(".")
        if parts[0] in ("np", "numpy") and parts[-1] in _HOST_SORTS:
            hit = name
        elif name == "sorted":
            hit = "sorted"
        else:
            continue
        out.append(Finding(
            ctx.rel, node.lineno, "host-sort",
            f"{hit} in the device ingest plane — posting "
            "sort/dedup/pack must stay on-chip (jnp.lexsort / "
            "segmented scans); host ordering belongs to the oracle "
            "pipeline in query/devindex.py"))
    return out


#: cross-chip collectives — the ICI traffic primitives. One module owns
#: them so the mesh topology (axis names, gather layout, replica
#: folding) has a single home; a collective elsewhere silently couples
#: that file to the serving mesh shape
_MESH_COLLECTIVES = {"all_gather", "psum", "pmean"}


def rule_mesh_collective(ctx: Ctx) -> list[Finding]:
    """``jax.lax.all_gather``/``psum``/``pmean`` outside
    parallel/sharded.py: cross-shard collectives belong to the mesh
    plane (the Msg3a merge program), not to per-shard kernels — scorer
    and devindex code must stay mesh-agnostic so the flat single-chip
    path runs it unchanged."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        tail = name.split(".")[-1] if name else ""
        if tail in _MESH_COLLECTIVES:
            out.append(Finding(
                ctx.rel, node.lineno, "mesh-collective",
                f"{tail} outside parallel/sharded.py — cross-shard "
                "collectives live in the mesh plane; keep per-shard "
                "kernels mesh-agnostic and merge in the shard_map "
                "program"))
    return out


def _mesh_collective_scope(rel: str) -> bool:
    return _in_pkg(rel) and rel != f"{PKG}/parallel/sharded.py"


# ---------------------------------------------------------------------------
# jit trace-discipline family
# ---------------------------------------------------------------------------

#: modules that OWN device↔host traffic: devindex's collect path and
#: scorer's packed fetch (the device-sync boundary) plus the mesh
#: path's replicated-output materialization in sharded.py
_JIT_TRANSFER_BOUNDARY = (
    f"{PKG}/query/devindex.py", f"{PKG}/query/scorer.py",
    f"{PKG}/parallel/sharded.py", f"{PKG}/build/devbuild.py")

_ARRAYISH_CALLS = {"np.array", "np.asarray", "numpy.array",
                   "numpy.asarray", "jnp.array", "jnp.asarray",
                   "jax.numpy.array", "jax.numpy.asarray"}

#: decorators that make a jit-wrapping factory safe (one wrapper per
#: distinct key, not one per call)
_CACHED_DECOS = {"lru_cache", "cache", "cached_property"}

_MATERIALIZERS = {"float", "int", "bool"}
_HOST_ARRAY_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                     "numpy.array"}
_MATERIALIZE_METHODS = {"item", "tolist", "__array__"}


def _is_jax_jit(node: ast.AST) -> bool:
    return dotted(node) == "jax.jit"


def _jit_wrap_call(node: ast.Call) -> bool:
    """``jax.jit(...)`` or ``[functools.]partial(jax.jit, ...)``."""
    if _is_jax_jit(node.func):
        return True
    fn = dotted(node.func)
    return fn in ("partial", "functools.partial") \
        and bool(node.args) and _is_jax_jit(node.args[0])


def _jit_kwargs(call: ast.Call) -> tuple[set[str], set[int]]:
    """(static_argnames, donate_argnums) literals of a jit wrap."""
    statics: set[str] = set()
    donate: set[int] = set()
    for kw in call.keywords:
        vals = kw.value.elts if isinstance(kw.value, ast.Tuple) \
            else [kw.value]
        if kw.arg == "static_argnames":
            statics |= {v.value for v in vals
                        if isinstance(v, ast.Constant)
                        and isinstance(v.value, str)}
        elif kw.arg == "donate_argnums":
            donate |= {v.value for v in vals
                       if isinstance(v, ast.Constant)
                       and isinstance(v.value, int)}
    return statics, donate


@dataclass
class _JitSite:
    name: str
    statics: set
    donate: set
    def_node: ast.FunctionDef | None


def _jit_registry(ctx: Ctx) -> dict[str, _JitSite]:
    """Per-file map of names bound to jit-wrapped callables: decorated
    defs (``@jax.jit`` / ``@partial(jax.jit, ...)``) plus module-level
    ``name = jax.jit(fn, ...)`` rebinds."""
    reg = getattr(ctx, "_jit_reg", None)
    if reg is not None:
        return reg
    reg = {}
    defs = {n.name: n for n in ast.walk(ctx.tree)
            if isinstance(n, ast.FunctionDef)}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef):
            for deco in node.decorator_list:
                if _is_jax_jit(deco):
                    statics, donate = set(), set()
                elif isinstance(deco, ast.Call) and _jit_wrap_call(deco):
                    statics, donate = _jit_kwargs(deco)
                else:
                    continue
                reg[node.name] = _JitSite(node.name, statics, donate,
                                          node)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _is_jax_jit(node.value.func):
            statics, donate = _jit_kwargs(node.value)
            inner = node.value.args[0] if node.value.args else None
            def_node = defs.get(inner.id) \
                if isinstance(inner, ast.Name) else None
            reg[node.targets[0].id] = _JitSite(
                node.targets[0].id, statics, donate, def_node)
    ctx._jit_reg = reg
    return reg


def _enclosing_function(ctx: Ctx, node: ast.AST):
    for _c, p in ctx.ancestors(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return None


def _local_exprs(fn: ast.AST | None) -> dict[str, list[ast.AST]]:
    """name → RHS expressions assigned to it inside ``fn`` — the few
    hops of local dataflow static-arg provenance needs."""
    out: dict[str, list[ast.AST]] = {}
    if fn is None:
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out.setdefault(node.targets[0].id, []).append(node.value)
        elif isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Name):
            out.setdefault(node.target.id, []).append(node.value)
    return out


def _value_nodes(expr: ast.AST):
    """Like ast.walk, but skips ``IfExp`` tests: a conditional
    quantizes a value into its branch set (``A if n <= A else B`` is
    two-valued however ``n`` was derived), so sizes read only in the
    test don't make the value unstable."""
    stack = [expr]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, ast.IfExp):
            stack.extend((node.body, node.orelse))
        else:
            stack.extend(ast.iter_child_nodes(node))


def _expr_matches(expr, amap, pred, depth=4, seen=None) -> bool:
    """Does ``pred`` hit any node of ``expr``, chasing local Name
    assignments up to ``depth`` hops?"""
    if seen is None:
        seen = set()
    for node in _value_nodes(expr):
        if pred(node):
            return True
        if depth > 0 and isinstance(node, ast.Name) \
                and isinstance(node.ctx, ast.Load) \
                and node.id not in seen and node.id in amap:
            seen.add(node.id)
            for rhs in amap[node.id]:
                if _expr_matches(rhs, amap, pred, depth - 1, seen):
                    return True
    return False


def _is_len_or_shape(node: ast.AST) -> bool:
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "len":
        return True
    # x.shape[i] — a runtime size is just as unstable as len()
    return isinstance(node, ast.Subscript) \
        and isinstance(node.value, ast.Attribute) \
        and node.value.attr == "shape"


def _is_bucketish(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        ident = _final_ident(node.func)
        return ident is not None and "bucket" in ident.lower()
    return False


def rule_jit_unstable_static(ctx: Ctx) -> list[Finding]:
    """Unstable value passed to a static_argnames parameter — every
    distinct value is a fresh XLA compile (retrace cliff + unbounded
    jit cache)."""
    reg = _jit_registry(ctx)
    out: list[Finding] = []
    if not reg:
        return out
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in reg):
            continue
        site = reg[node.func.id]
        if not site.statics:
            continue
        amap = _local_exprs(_enclosing_function(ctx, node))
        for kw in node.keywords:
            if kw.arg not in site.statics:
                continue
            frag = None
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) \
                        and isinstance(n.value, float):
                    frag = "a float"
                elif isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Name) \
                        and n.func.id == "float":
                    frag = "a float()"
                elif isinstance(n, (ast.Dict, ast.List, ast.Set,
                                    ast.DictComp, ast.ListComp,
                                    ast.SetComp)):
                    frag = "an unhashable container"
                elif isinstance(n, ast.Call) \
                        and dotted(n.func) in _ARRAYISH_CALLS:
                    frag = "an array value"
                if frag:
                    break
            if frag is None \
                    and _expr_matches(kw.value, amap, _is_len_or_shape) \
                    and not _expr_matches(kw.value, amap, _is_bucketish):
                frag = "a len()/shape-derived value with no bucket " \
                       "rounding"
            if frag:
                out.append(Finding(
                    ctx.rel, kw.value.lineno, "jit-unstable-static",
                    f"{frag} passed to static arg '{kw.arg}' of "
                    f"{node.func.id}() — every distinct value is a "
                    "fresh XLA compile; statics must be bucketed "
                    "stable ints/bools (query/packer._bucket)"))
    return out


def rule_jit_in_body(ctx: Ctx) -> list[Finding]:
    """jax.jit wrapped inside a function body — a fresh wrapper (and
    empty compile cache) per call, so nothing is ever warm."""
    out: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and _jit_wrap_call(node)):
            continue
        encl = None
        for child, parent in ctx.ancestors(node):
            if isinstance(parent, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                if child in parent.decorator_list:
                    continue  # decorator position == module-level wrap
                encl = parent
                break
        if encl is None:
            continue
        if any(_final_ident(d) in _CACHED_DECOS
               for d in encl.decorator_list):
            continue  # memoized factory: one wrapper per key
        out.append(Finding(
            ctx.rel, node.lineno, "jit-in-body",
            f"jax.jit inside {encl.name}() — a fresh wrapper (and "
            "compile cache) per call; hoist to module level or "
            "memoize the factory with lru_cache"))
    return out


def _jit_body_scope(rel: str) -> bool:
    return any(rel.startswith(f"{PKG}/{d}/")
               for d in ("query", "parallel", "serve"))


def rule_jit_mutable_closure(ctx: Ctx) -> list[Finding]:
    """A jitted function reading module-level mutable state — the
    value is frozen into the traced program and silently goes stale
    when the container mutates."""
    reg = _jit_registry(ctx)
    muts = _module_mutables(ctx.tree)
    out: list[Finding] = []
    if not (reg and muts):
        return out
    for site in reg.values():
        fn = site.def_node
        if fn is None:
            continue
        a = fn.args
        local = {p.arg for p in
                 a.args + a.kwonlyargs + a.posonlyargs}
        for va in (a.vararg, a.kwarg):
            if va is not None:
                local.add(va.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Store):
                local.add(node.id)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in muts and node.id not in local:
                out.append(Finding(
                    ctx.rel, node.lineno, "jit-mutable-closure",
                    f"jitted {fn.name}() reads module-level mutable "
                    f"'{node.id}' at trace time — the traced value is "
                    "frozen into the compiled program and goes stale "
                    "when the container mutates; pass it as an "
                    "argument"))
    return out


def rule_jit_donated_reuse(ctx: Ctx) -> list[Finding]:
    """An argument donated via donate_argnums read after the donating
    call — donation deallocates the buffer; the read returns garbage
    (or crashes) on real backends."""
    reg = _jit_registry(ctx)
    donators = {n: s for n, s in reg.items() if s.donate}
    out: list[Finding] = []
    if not donators:
        return out
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in donators):
            continue
        site = donators[node.func.id]
        encl = _enclosing_function(ctx, node)
        if encl is None:
            continue
        targets: set[str] = set()
        parent = ctx.parents.get(node)
        if isinstance(parent, ast.Assign) and parent.value is node:
            targets = {dotted(t) for t in parent.targets} - {None}
        end = getattr(node, "end_lineno", node.lineno)
        for pos in site.donate:
            if pos >= len(node.args):
                continue
            dn = dotted(node.args[pos])
            if dn is None or dn in targets:
                continue  # rebind of the donated name: the safe idiom
            for later in ast.walk(encl):
                if isinstance(later, (ast.Name, ast.Attribute)) \
                        and later.lineno > end \
                        and isinstance(getattr(later, "ctx", None),
                                       ast.Load) \
                        and dotted(later) == dn:
                    out.append(Finding(
                        ctx.rel, later.lineno, "jit-donated-reuse",
                        f"'{dn}' donated to {node.func.id}() on line "
                        f"{node.lineno} is read afterwards — donation "
                        "deallocates the buffer; rebind the result to "
                        f"'{dn}' or drop donate_argnums"))
                    break
    return out


def _device_producer(call: ast.Call, reg) -> bool:
    name = dotted(call.func)
    if name is None:
        return False
    if isinstance(call.func, ast.Name) and name in reg:
        return True
    return name.startswith(("jnp.", "jax.numpy.")) \
        or name == "jax.device_put"


def rule_jit_implicit_transfer(ctx: Ctx) -> list[Finding]:
    """float()/.item()/np.asarray()/.tolist() on a device value
    outside the device-boundary modules — an implicit device→host
    sync on the request path."""
    reg = _jit_registry(ctx)
    # device-valued local names: single-name targets assigned from a
    # jit-wrapped or jnp-producing call, keyed by enclosing function
    dev_by_fn: dict[int, set[str]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _device_producer(node.value, reg):
            fnkey = id(_enclosing_function(ctx, node) or ctx.tree)
            dev_by_fn.setdefault(fnkey, set()).add(node.targets[0].id)

    def is_dev(expr: ast.AST, fnkey: int) -> bool:
        if isinstance(expr, ast.Name) \
                and expr.id in dev_by_fn.get(fnkey, ()):
            return True
        return isinstance(expr, ast.Call) \
            and _device_producer(expr, reg)

    out: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fnkey = id(_enclosing_function(ctx, node) or ctx.tree)
        name = dotted(node.func)
        hit = None
        if isinstance(node.func, ast.Name) \
                and name in _MATERIALIZERS \
                and node.args and is_dev(node.args[0], fnkey):
            hit = f"{name}()"
        elif name in _HOST_ARRAY_CALLS and node.args \
                and is_dev(node.args[0], fnkey):
            hit = f"{name}()"
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MATERIALIZE_METHODS \
                and is_dev(node.func.value, fnkey):
            hit = f".{node.func.attr}()"
        if hit:
            out.append(Finding(
                ctx.rel, node.lineno, "jit-implicit-transfer",
                f"{hit} on a device value outside the device boundary "
                "— an implicit host sync serializes the pipeline; "
                "fetch at the boundary (devindex collect / scorer / "
                "sharded) or keep the value on device"))
    return out


def _jit_transfer_scope(rel: str) -> bool:
    return _in_pkg(rel) and rel not in _JIT_TRANSFER_BOUNDARY


def rule_bare_deadline(ctx: Ctx) -> list[Finding]:
    """Hand-rolled deadline arithmetic on the budgeted paths.

    ``time.monotonic() + timeout`` mints a deadline no header stamps
    and no abandon checkpoint sees; ``x - time.monotonic()`` is its
    remaining-time read. Both must come through
    ``utils.deadline.Deadline``. Duration measurement
    (``time.monotonic() - t0``: the time call on the LEFT of the
    subtraction) is not a deadline and stays legal."""
    def is_now(expr: ast.AST) -> bool:
        return (isinstance(expr, ast.Call)
                and dotted(expr.func) in ("time.time",
                                          "time.monotonic"))

    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.BinOp):
            continue
        if isinstance(node.op, ast.Add) \
                and (is_now(node.left) or is_now(node.right)):
            what = "now + budget mints a deadline"
        elif isinstance(node.op, ast.Sub) and is_now(node.right):
            what = "x - now reads a hand-rolled deadline"
        else:
            continue
        out.append(Finding(
            ctx.rel, node.lineno, "bare-deadline",
            f"{what} outside the Deadline helper — use "
            "utils.deadline.Deadline (.after/.remaining/.clamp) so "
            "the budget rides X-OSSE-Deadline and the "
            "deadline.abandoned counters can't be bypassed"))
    return out


def rule_adhoc_timing(ctx: Ctx) -> list[Finding]:
    """Ad-hoc latency measurement on the timed paths.

    ``time.perf_counter() - t0`` (or ``time.time() - t0``) computes a
    duration the aggregate plane and the trace plane never see — the
    two-timing-planes-drift bug class: a latency that shows up in a
    log line but not on /admin/perf, or vice versa. Measured intervals
    come through ``trace.timed_span`` (measures for you) or
    ``trace.record`` (attributes an interval you timed yourself) —
    both feed g_stats AND the waterfall. ``time.monotonic() - t0``
    stays legal: that is elapsed-budget arithmetic (deadlines,
    backoff), not a latency measurement."""
    def is_clock(expr: ast.AST) -> bool:
        return (isinstance(expr, ast.Call)
                and dotted(expr.func) in ("time.perf_counter",
                                          "time.time"))

    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) \
                and is_clock(node.left):
            out.append(Finding(
                ctx.rel, node.lineno, "adhoc-timing",
                "ad-hoc clock delta measures a latency neither "
                "/admin/perf nor the trace waterfall will see — use "
                "trace.timed_span (or trace.record for an interval "
                "you timed yourself); both feed g_stats AND the "
                "trace plane"))
    return out


def _admission_scope(rel: str) -> bool:
    """serve/ routes only — admission.py IS the gate, and the other
    planes (query/, parallel/) sit below it by design."""
    return rel.startswith(f"{PKG}/serve/") \
        and not rel.endswith("/admission.py")


#: serve/ functions allowed to touch the dispatch planes directly:
#: the one call site that runs AFTER AdmissionGate.admit()
_ADMISSION_SANCTIONED = {"_render_search"}


def rule_admission_bypass(ctx: Ctx) -> list[Finding]:
    """Dispatch-plane calls from serve/ that skip the admission gate.

    ``_batcher.search(...)`` / ``get_resident_loop(...).submit(...)``
    from a serve route hands work to the device planes without
    admission control — under overload that path grows an unbounded
    queue and bypasses the tier/shed accounting the load gates assert
    on. Route through ``AdmissionGate.admit()`` first (the sanctioned
    call site is ``_render_search``, which runs under the admitted
    token)."""
    #: names bound from get_resident_loop(...) anywhere in the file —
    #: one hop of dataflow catches `loop = get_resident_loop(c)`
    tainted: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and _final_ident(node.value.func) \
                == "get_resident_loop":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    tainted.add(t.id)

    def bypasses(node: ast.Call) -> str | None:
        if not isinstance(node.func, ast.Attribute):
            return None
        val = node.func.value
        chain = dotted(val) or ""
        if node.func.attr == "search" \
                and chain.endswith("_batcher"):
            return f"{chain}.search()"
        if node.func.attr == "submit" and (
                "resident" in chain
                or (isinstance(val, ast.Call)
                    and _final_ident(val.func) == "get_resident_loop")
                or (isinstance(val, ast.Name)
                    and val.id in tainted)):
            return "resident submit()"
        return None

    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        hit = bypasses(node)
        if hit is None:
            continue
        fn = _enclosing_function(ctx, node)
        if fn is not None and fn.name in _ADMISSION_SANCTIONED:
            continue
        out.append(Finding(
            ctx.rel, node.lineno, "admission-bypass",
            f"{hit} from a serve route skips the admission gate — "
            "unbounded queueing and untiered overload; go through "
            "AdmissionGate.admit() (only _render_search may touch "
            "the dispatch planes directly)"))
    return out


def _conc_scope(rel: str) -> bool:
    """The planes whose objects real threads share — the schedcheck
    scenario surface: query/, serve/, parallel/, cache/."""
    return any(rel.startswith(f"{PKG}/{d}/")
               for d in ("query", "serve", "parallel", "cache"))


#: constructor-shaped methods whose writes happen before the object is
#: published to other threads (dataclasses run __post_init__ inside
#: generated __init__)
_PREPUB = ("__init__", "__post_init__")


def _locked_method(fn: ast.AST) -> bool:
    """The repo's caller-holds-the-lock conventions: ``*_locked``
    method names (admission.py) and locked-ish decorators (rdblite's
    ``@_locked``) mean the lock is held on entry — writes inside are
    protected even without a lexical ``with``."""
    if fn.name.endswith("_locked"):
        return True
    return any((_final_ident(d) or "").endswith("locked")
               for d in fn.decorator_list)


def _self_attr(node: ast.AST) -> str | None:
    """``x`` for a ``self.x`` expression, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _body_stmts(body: list[ast.stmt]):
    """Every node lexically in ``body``, NOT descending into nested
    function/lambda definitions (closures run later, elsewhere)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def rule_shared_state_unlocked(ctx: Ctx) -> list[Finding]:
    """Per class: a ``self.``-attribute written under a lock in one
    method (lexical ``with <lockish>:``, a ``*_locked`` name, or a
    locked decorator) but without one in another. That split is the
    lost-update shape schedcheck's explorer demonstrates dynamically —
    two writers interleaving between read and write. ``__init__``
    writes are pre-publication and exempt both ways."""
    out = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = [n for n in cls.body if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        #: (attr, write node, method name, lock held)
        writes: list[tuple[str, ast.AST, str, bool]] = []
        for m in methods:
            if m.name in _PREPUB:
                continue
            held = _locked_method(m)
            for node in ast.walk(m):
                if not isinstance(node, (ast.Assign, ast.AugAssign)):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        writes.append((attr, node, m.name,
                                       held or _under_lock(ctx, node)))
        locked_in: dict[str, set[str]] = {}
        for attr, _node, mname, prot in writes:
            if prot:
                locked_in.setdefault(attr, set()).add(mname)
        seen: set[tuple[str, int]] = set()
        for attr, node, mname, prot in writes:
            if prot:
                continue
            others = locked_in.get(attr, set()) - {mname}
            if not others or (attr, node.lineno) in seen:
                continue
            seen.add((attr, node.lineno))
            out.append(Finding(
                ctx.rel, node.lineno, "shared-state-unlocked",
                f"self.{attr} written without a lock here but under "
                f"one in {sorted(others)[0]}() — a thread can "
                "interleave between the two writers (the lost-update "
                "shape schedcheck explores); take the same lock"))
    return out


def rule_check_then_act(ctx: Ctx) -> list[Finding]:
    """``if k in self.d:`` / ``if self.x is None:`` followed by a
    mutation of the SAME shared container/attribute, outside any lock
    body: the classic TOCTOU — another thread can act between the
    check and the act. Lock-holding conventions (``with <lockish>:``,
    ``*_locked`` names, locked decorators) exempt the site."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.If):
            continue
        fn = _enclosing_function(ctx, node)
        if fn is None or fn.name in _PREPUB or _locked_method(fn):
            continue
        if _under_lock(ctx, node):
            continue
        test, attr, shape = node.test, None, None
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            op = test.ops[0]
            if isinstance(op, (ast.In, ast.NotIn)):
                attr = _self_attr(test.comparators[0])
                shape = "membership"
            elif isinstance(op, (ast.Is, ast.IsNot)) \
                    and isinstance(test.comparators[0], ast.Constant) \
                    and test.comparators[0].value is None:
                attr = _self_attr(test.left)
                shape = "none"
        if attr is None:
            continue
        for sub in _body_stmts(node.body):
            hit = False
            if isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and _self_attr(t.value) == attr:
                        hit = True
                    elif shape == "none" and _self_attr(t) == attr:
                        hit = True
            elif isinstance(sub, ast.Delete):
                hit = any(isinstance(t, ast.Subscript)
                          and _self_attr(t.value) == attr
                          for t in sub.targets)
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATORS \
                    and _self_attr(sub.func.value) == attr:
                hit = True
            if hit:
                out.append(Finding(
                    ctx.rel, sub.lineno, "check-then-act",
                    f"self.{attr} checked then mutated without a lock "
                    "— another thread can act between the check and "
                    "this write (TOCTOU); hold the owning lock across "
                    "both"))
                break
    return out


def rule_cond_wait_no_loop(ctx: Ctx) -> list[Finding]:
    """``Condition.wait`` not inside a ``while`` predicate loop.
    Spurious wakeups and notify_all herds make a bare ``wait()`` (or
    an ``if``-guarded one) return with the predicate false; every wait
    must re-check in a loop — the shape schedcheck's notify scheduling
    exercises directly."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr != "wait" \
                or not _is_lockish(node.func.value):
            continue
        in_while = False
        for _child, parent in ctx.ancestors(node):
            if isinstance(parent, ast.While):
                in_while = True
                break
            if isinstance(parent, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.Lambda)):
                break
        if not in_while:
            out.append(Finding(
                ctx.rel, node.lineno, "cond-wait-no-loop",
                "Condition.wait outside a while predicate loop — "
                "spurious wakeups / notify_all herds return with the "
                "predicate false; wrap in `while not <predicate>:`"))
    return out


#: (rule-name, path predicate, checker)
RULES = [
    ("ttlcache-offplane", _ttl_scope, rule_ttlcache_offplane),
    ("urllib-in-parallel", _urllib_scope, rule_urllib_in_parallel),
    ("bare-stats-timed", _timed_scope, rule_bare_stats_timed),
    ("stats-cardinality", _stats_name_scope, rule_stats_cardinality),
    ("id-key", _in_pkg, rule_id_key),
    ("blocking-under-lock", _in_pkg, rule_blocking_under_lock),
    ("silent-except", _scope_pkg_tools, rule_silent_except),
    ("mutable-default", _scope_pkg_tools, rule_mutable_default),
    ("thread-spawn", _thread_scope, rule_thread_spawn),
    ("locked-global", _locked_global_scope, rule_locked_global),
    ("device-sync", _device_scope, rule_device_sync),
    ("host-sort", _devbuild_scope, rule_host_sort),
    ("mesh-collective", _mesh_collective_scope, rule_mesh_collective),
    ("jit-unstable-static", _in_pkg, rule_jit_unstable_static),
    ("jit-in-body", _jit_body_scope, rule_jit_in_body),
    ("jit-mutable-closure", _in_pkg, rule_jit_mutable_closure),
    ("jit-donated-reuse", _in_pkg, rule_jit_donated_reuse),
    ("jit-implicit-transfer", _jit_transfer_scope,
     rule_jit_implicit_transfer),
    ("bare-deadline", _timed_scope, rule_bare_deadline),
    ("adhoc-timing", _timed_scope, rule_adhoc_timing),
    ("admission-bypass", _admission_scope, rule_admission_bypass),
    ("proc-spawn", _proc_scope, rule_proc_spawn),
    ("residency-bypass", _residency_scope, rule_residency_bypass),
    ("shared-state-unlocked", _conc_scope, rule_shared_state_unlocked),
    ("check-then-act", _conc_scope, rule_check_then_act),
    ("cond-wait-no-loop", _in_pkg, rule_cond_wait_no_loop),
]

RULE_NAMES = {name for name, _p, _c in RULES}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def check_source(src: str, rel: str) -> list[Finding]:
    """Lint one source text as if it lived at ``rel`` (posix relative
    path — rule scoping keys off it). The fixture/test entry point."""
    rel = rel.replace("\\", "/")
    for line in src.splitlines()[:5]:
        m = _PATH_PRAGMA_RE.search(line)
        if m:
            rel = m.group(1)
            break
    try:
        ctx = Ctx(src, rel)
    except SyntaxError as exc:
        return [Finding(rel, exc.lineno or 1, "syntax-error", str(exc))]
    findings: list[Finding] = []
    for name, pred, checker in RULES:
        if not pred(rel):
            continue
        for f in checker(ctx):
            if name in ctx.waivers.get(f.line, ()):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings


def default_paths(root: Path) -> list[Path]:
    return [root / PKG, root / "tools", root / "tests"]


def iter_py_files(paths: list[Path], root: Path) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not EXCLUDE_PARTS & set(f.relative_to(root).parts):
                    out.append(f)
    return out


def changed_files(root: Path) -> list[Path]:
    """Files touched vs. HEAD: unstaged + staged + untracked.

    Parsed from NUL-separated ``--name-status`` records so rename and
    delete entries are handled explicitly: a rename (``R``/``C``, two
    path fields) contributes its NEW path, a deletion contributes
    nothing (the old path no longer exists to lint), and ``-z``
    sidesteps git's path quoting for unusual filenames."""
    import subprocess
    names: set[str] = set()
    for args in (["git", "diff", "--name-status", "-z", "-M", "HEAD"],
                 ["git", "diff", "--name-status", "-z", "-M",
                  "--cached"]):
        proc = subprocess.run(args, cwd=root, capture_output=True,
                              text=True, check=False)
        fields = proc.stdout.split("\0")
        i = 0
        while i < len(fields):
            status = fields[i].strip()
            if not status:
                i += 1
                continue
            if status[0] in "RC":  # rename/copy: status, old, new
                if i + 2 < len(fields) and fields[i + 2]:
                    names.add(fields[i + 2])
                i += 3
            elif status[0] == "D":  # deletion: nothing left to lint
                i += 2
            else:
                if i + 1 < len(fields) and fields[i + 1]:
                    names.add(fields[i + 1])
                i += 2
    proc = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard", "-z"],
        cwd=root, capture_output=True, text=True, check=False)
    names.update(n for n in proc.stdout.split("\0") if n)
    out = []
    for n in sorted(names):
        p = root / n
        if p.suffix == ".py" and p.exists() \
                and not (EXCLUDE_PARTS & set(Path(n).parts)):
            out.append(p)
    return out


def lint_files(files: list[Path], root: Path) -> list[Finding]:
    findings: list[Finding] = []
    for f in files:
        rel = f.relative_to(root).as_posix()
        try:
            src = f.read_text(encoding="utf-8")
        except OSError as exc:
            findings.append(Finding(rel, 1, "unreadable", str(exc)))
            continue
        findings.extend(check_source(src, rel))
    return findings


def main(argv: list[str] | None = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="osselint", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: package + "
                         "tools + tests)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files changed vs. git HEAD")
    ap.add_argument("--root", default=None,
                    help="repo root (default: this file's repo)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, _pred, checker in RULES:
            doc = (checker.__doc__ or "").strip().splitlines()
            print(f"{name}: {doc[0] if doc else ''}")
        return 0

    root = Path(args.root).resolve() if args.root \
        else Path(__file__).resolve().parent.parent
    if args.changed:
        files = changed_files(root)
    elif args.paths:
        files = iter_py_files([Path(p).resolve() for p in args.paths],
                              root)
    else:
        files = iter_py_files(default_paths(root), root)

    findings = lint_files(files, root)
    if args.format == "json":
        print(json.dumps({"files": len(files),
                          "findings": [f.as_dict() for f in findings]},
                         indent=2))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}: {f.rule}: {f.msg}")
        print(f"osselint: {len(files)} files, "
              f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
