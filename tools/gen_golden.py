"""Regenerate the golden QA expectations (tests/golden/expected.json).

Run after an INTENTIONAL scoring change, inspect the diff, and commit —
the reference's qa.cpp golden-CRC model (qa.cpp:3358): the expected
(docid, score) outputs are pinned so any silent ranking drift fails CI
with a readable diff.
"""
import json
import os
import sys
import tempfile
from pathlib import Path

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

from open_source_search_engine_tpu.build import docproc  # noqa: E402
from open_source_search_engine_tpu.index.collection import Collection  # noqa: E402
from open_source_search_engine_tpu.query import engine  # noqa: E402
from tests.golden.corpus import GOLDEN_QUERIES, golden_docs  # noqa: E402


def main() -> None:
    coll = Collection("golden", tempfile.mkdtemp(prefix="osse_golden_"))
    coll.conf.pqr_enabled = False  # goldens pin the kernel ranking
    for url, html in golden_docs().items():
        docproc.index_document(coll, url, html)
    out = {}
    for q in GOLDEN_QUERIES:
        # topk=50 captures whole tie groups: the checkers compare the
        # tested paths' (smaller) result pages as per-score subsets
        res = engine.search(coll, q, topk=50, site_cluster=False,
                            with_snippets=False)
        out[q] = {
            "total": res.total_matches,
            "results": [[int(r.docid), round(float(r.score), 2)]
                        for r in res.results],
        }
    path = Path(__file__).resolve().parent.parent / "tests" / "golden" \
        / "expected.json"
    path.write_text(json.dumps(out, indent=1, sort_keys=True) + "\n")
    print(f"wrote {path} ({len(out)} queries)")


if __name__ == "__main__":
    main()
