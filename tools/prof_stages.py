"""Stage-ablation profiling of the two kernels at 100k docs."""
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import bench
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.compiler import compile_query
from open_source_search_engine_tpu.query.scorer import (
    final_multipliers, min_scores, presence_table_ok)
import open_source_search_engine_tpu.query.devindex as dv

STAGE = int(os.environ.get("STAGE", "9"))


@partial(jax.jit, static_argnames=("n_positions", "lsp", "kappa", "k2",
                                   "stage"))
def f1_staged(d_payload, d_doc, d_imp, d_rsp, d_dense_imp, d_dense_rsp,
              d_siterank, d_doclang, d_dead, n_docs_total,
              d_slot, d_group, d_base, d_quota, d_syn,
              s_start, s_len, s_group, s_base, s_quota, s_syn, s_isbase,
              freqw, required, negative, scored, counts, table, qlang,
              n_positions: int, lsp: int, kappa: int, k2: int, stage: int):
    D = d_dead.shape[0]
    V = d_dense_imp.shape[0]
    M = d_doc.shape[0]
    N = d_payload.shape[0]
    P = n_positions
    big = jnp.float32(9.99e8)

    def one(d_slot, d_group, d_base, d_quota, d_syn,
            s_start, s_len, s_group, s_base, s_quota, s_syn, s_isbase,
            freqw, required, negative, scored, counts, table, qlang):
        T = required.shape[0]
        Rd = d_slot.shape[0]
        Rs = s_start.shape[0]
        t_ax = jnp.arange(T)
        live = ~d_dead
        ubb = jnp.zeros((T, D), jnp.float32)
        dimp = d_dense_imp[jnp.clip(d_slot, 0, V - 1)]
        dgate = (d_slot >= 0)
        for r in range(Rd):
            contrib = jnp.where(dgate[r], dimp[r], 0.0)
            ubb = ubb + jnp.where((d_group[r] == t_ax)[:, None],
                                  contrib[None, :], 0.0)
        if stage == 0:
            return ubb.sum(axis=0)[:2 + 2 * k2]
        lane = jnp.arange(lsp, dtype=jnp.int32)
        sidx = s_start[:, None] + lane[None, :]
        smask = lane[None, :] < s_len[:, None]
        sidxc = jnp.clip(sidx, 0, M - 1)
        sdoc = d_doc[sidxc]
        simp = d_imp[sidxc]
        srsp = d_rsp[sidxc]
        side = jnp.where(s_isbase, 0, T * D)[:, None]
        tgt = jnp.where(smask, side + s_group[:, None] * D + sdoc,
                        2 * T * D)
        ub2 = jnp.zeros((2 * T * D,), jnp.float32).at[tgt.ravel()].add(
            jnp.where(smask, simp, 0.0).ravel(), mode="drop"
        ).reshape(2, T, D)
        ubb = ubb + ub2[0]
        ubd = ub2[1]
        ub = ubb * live[None, :] + ubd
        rstgt = jnp.where(
            smask, jnp.arange(Rs, dtype=jnp.int32)[:, None] * D + sdoc,
            Rs * D)
        rsacc = jnp.zeros((Rs * D,), jnp.int32).at[rstgt.ravel()].set(
            jnp.where(smask, srsp, 0).ravel(), mode="drop")
        if stage == 1:
            return (ub.sum(axis=0) + rsacc[:D])[:2 + 2 * k2]
        present = ub > 0.0
        sc = counts
        ubw = ub * (freqw * freqw)[:, None]
        req_ok = jnp.all(jnp.where(required[:, None], present, True),
                         axis=0)
        neg_ok = ~jnp.any(jnp.where(negative[:, None], present, False),
                          axis=0)
        alive = (req_ok & neg_ok & presence_table_ok(present, table)
                 & (jnp.arange(D) < n_docs_total))
        m1 = present & sc[:, None]
        min_single_ub = jnp.min(jnp.where(m1, ubw, big), axis=0)
        min_pair_ub = jnp.full((D,), big)
        any_pair = jnp.zeros((D,), bool)
        for i in range(T):
            for j in range(i + 1, T):
                ok = present[i] & present[j] & sc[i] & sc[j]
                pu = jnp.sqrt(ubw[i] * ubw[j])
                min_pair_ub = jnp.where(ok, jnp.minimum(min_pair_ub, pu),
                                        min_pair_ub)
                any_pair = any_pair | ok
        ubmin = jnp.minimum(jnp.where(any_pair, min_pair_ub, big),
                            min_single_ub)
        ubmin = jnp.where(jnp.any(sc), ubmin, 1.0)
        mult = final_multipliers(d_siterank, d_doclang, qlang)
        ubfinal = jnp.where(alive, ubmin * mult * 1.00001, 0.0)
        nm = jnp.sum(alive)
        if stage == 2:
            return ubfinal[:2 + 2 * k2]
        cval, cand, ub_missed = dv._block_top2(ubfinal, kappa)
        if stage == 3:
            return (cval + cand)[:2 + 2 * k2]
        dead_c = d_dead[cand]
        p_ax = jnp.arange(P, dtype=jnp.int32)[:, None]
        cube = jnp.zeros((T, P, kappa), jnp.uint32)
        pv = jnp.zeros((T, P, kappa), bool)

        def add_row(cube, pv, rsp_c, group, base, quota, syn, is_base):
            rs = (rsp_c >> 5).astype(jnp.int32)
            cnt = rsp_c & 31
            cnt = jnp.where(is_base & dead_c, 0, cnt)
            q = p_ax - base
            sel = (q >= 0) & (q < jnp.minimum(cnt, quota)[None, :])
            src = rs[None, :] + q
            val = (d_payload[jnp.clip(src, 0, N - 1)]
                   | (syn.astype(jnp.uint32) << jnp.uint32(31)))
            gmask = (group == t_ax)[:, None, None]
            cube = cube + jnp.where(sel, val, jnp.uint32(0))[None] \
                * gmask.astype(jnp.uint32)
            pv = pv | (sel[None] & gmask)
            return cube, pv

        dense_rsp_c = d_dense_rsp[
            jnp.clip(d_slot, 0, V - 1)[:, None] * D + cand[None, :]]
        for r in range(Rd):
            rsp_c = jnp.where(dgate[r], dense_rsp_c[r], 0)
            cube, pv = add_row(cube, pv, rsp_c, d_group[r], d_base[r],
                               d_quota[r], d_syn[r], True)
        for r in range(Rs):
            rsp_c = rsacc[r * D + cand]
            cube, pv = add_row(cube, pv, rsp_c, s_group[r], s_base[r],
                               s_quota[r], s_syn[r], s_isbase[r])
        if stage == 4:
            return cube.sum(axis=(0, 1))[:2 + 2 * k2].astype(jnp.float32)
        min_sc, present2 = min_scores(cube, pv, freqw, sc)
        if stage == 5:
            return min_sc[:2 + 2 * k2]
        req_ok2 = jnp.all(jnp.where(required[:, None], present2, True),
                          axis=0)
        neg_ok2 = ~jnp.any(jnp.where(negative[:, None], present2, False),
                           axis=0)
        match2 = (req_ok2 & neg_ok2 & presence_table_ok(present2, table)
                  & (cval > 0.0) & (min_sc < big))
        final = jnp.where(
            match2,
            min_sc * final_multipliers(d_siterank[cand], d_doclang[cand],
                                       qlang),
            0.0)
        ts, tl = jax.lax.top_k(final, k2)
        return jnp.concatenate([ts, tl.astype(jnp.float32)])

    return jax.vmap(one)(d_slot, d_group, d_base, d_quota, d_syn,
                         s_start, s_len, s_group, s_base, s_quota, s_syn,
                         s_isbase, freqw, required, negative, scored,
                         counts, table, qlang)


def main():
    coll = Collection("bench", os.environ.get("BENCH_DIR", "/root/bench_cache/b100k"))
    di = engine.get_device_index(coll)
    print(f"ready D={di.D_cap}", flush=True)
    qs = bench._make_queries(3000, seed=11)
    f2_cut = min(dv.CUBE_MIN_DF, max(2 * dv.KAPPA_FLOOR, di.n_docs // 8))
    f1_plans = []
    for q in qs:
        p = di.plan(compile_query(q, 0))
        if p.matchable and not (p.driver_df > f2_cut) \
                and di._kappa_of(p, 64) == 2048:
            f1_plans.append(p)
        if len(f1_plans) >= 32 * 8:
            break
    print(f"{len(f1_plans)} kappa-2048 f1 plans", flush=True)

    def run(stage, i):
        plans = f1_plans[32 * i:32 * i + 32]
        Rd = dv._bucket(max([len(p.d_slot) for p in plans] + [1]),
                        dv.RD_FLOOR)
        Rs = dv._bucket(max([len(p.s_start) for p in plans] + [1]),
                        dv.RS_FLOOR)
        # reuse DeviceIndex's padding by calling its _run_batch-like prep
        out = di._run_batch(plans, 2048, 64)  # warm real path shapes
        return out

    # time the staged variants by monkeypatching _two_phase
    orig = dv._two_phase
    for stage in range(0, 7):
        dv._two_phase = partial(f1_staged, stage=stage)
        # compile (FETCH — block_until_ready lies on this backend until
        # the dispatch queue is flushed by a fetch)
        t0 = time.perf_counter()
        jax.device_get(di._run_batch(f1_plans[:32], 2048, 64))
        c = time.perf_counter() - t0
        times = []
        for i in range(1, 5):
            t0 = time.perf_counter()
            jax.device_get(di._run_batch(
                f1_plans[32 * i:32 * i + 32], 2048, 64))
            times.append(time.perf_counter() - t0)
        print(f"stage {stage}: {1000*min(times):.0f} ms "
              f"(compile {c:.0f}s)", flush=True)
    dv._two_phase = orig


if __name__ == "__main__":
    main()
