"""Stage-ablation of the F2 full-cube kernel at 100k docs."""
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import bench
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine, weights
from open_source_search_engine_tpu.query.compiler import compile_query
from open_source_search_engine_tpu.query.scorer import (
    QDIST, final_multipliers, position_weights, presence_table_ok)
from open_source_search_engine_tpu.index.posdb import (
    HASHGROUP_END, HASHGROUP_INLINKTEXT)
import open_source_search_engine_tpu.query.devindex as dv


@partial(jax.jit, static_argnames=("n_positions", "lpost", "k2", "stage"))
def f2_staged(d_payload, d_pdoc, d_pocc, d_cube, d_dense_rsp,
              d_siterank, d_doclang, d_dead, n_docs_total,
              c_slot, c_dslot, c_group, c_base, c_quota, c_syn,
              p_start, p_len, p_group, p_base, p_quota, p_syn, p_isbase,
              freqw, required, negative, scored, counts, table, qlang,
              n_positions: int, lpost: int, k2: int, stage: int,
              exact: bool = False):
    D = d_dead.shape[0]
    N = d_payload.shape[0]
    P = n_positions
    VcPD = d_cube.shape[0]
    big = jnp.float32(9.99e8)

    def one(c_slot, c_dslot, c_group, c_base, c_quota, c_syn,
            p_start, p_len, p_group, p_base, p_quota, p_syn, p_isbase,
            freqw, required, negative, scored, counts, table, qlang):
        T = required.shape[0]
        Rc = c_slot.shape[0]
        Rp = p_start.shape[0]
        t_ax = jnp.arange(T)
        live = ~d_dead
        p_ax = jnp.arange(P, dtype=jnp.int32)[:, None]
        cube = jnp.zeros((T, P, D), jnp.uint32)
        pv = jnp.zeros((T, P, D), bool)
        V = d_dense_rsp.shape[0] // D
        for r in range(Rc):
            gate = c_slot[r] >= 0
            row = jax.lax.dynamic_slice(
                d_cube, (jnp.clip(c_slot[r], 0, VcPD // (P * D) - 1)
                         * P * D,), (P * D,)).reshape(P, D)
            cnt = (jax.lax.dynamic_slice(
                d_dense_rsp, (jnp.clip(c_dslot[r], 0, V - 1) * D,),
                (D,)) & 31)
            q = p_ax[:, 0] - c_base[r]
            row = jnp.take(row, jnp.clip(q, 0, P - 1), axis=0)
            pvr = ((q[:, None] >= 0)
                   & (q[:, None] < jnp.minimum(cnt, c_quota[r])[None, :])
                   & live[None, :] & gate)
            val = row | (c_syn[r].astype(jnp.uint32) << jnp.uint32(31))
            gmask = (c_group[r] == t_ax)[:, None, None]
            cube = cube + jnp.where(pvr, val, jnp.uint32(0))[None] \
                * gmask.astype(jnp.uint32)
            pv = pv | (pvr[None] & gmask)
        if stage == 0:
            return cube.sum(axis=(0, 1))[:2 * k2].astype(jnp.float32)
        lane = jnp.arange(lpost, dtype=jnp.int32)
        idx = p_start[:, None] + lane[None, :]
        m = lane[None, :] < p_len[:, None]
        idxc = jnp.clip(idx, 0, N - 1)
        doc = d_pdoc[idxc]
        occ = d_pocc[idxc].astype(jnp.int32)
        pay = (d_payload[idxc]
               | (p_syn[:, None].astype(jnp.uint32) << jnp.uint32(31)))
        dead_l = d_dead[jnp.clip(doc, 0, D - 1)]
        ok = (m & (occ < p_quota[:, None]) & ~(dead_l & p_isbase[:, None]))
        slot = p_base[:, None] + occ
        tgt = jnp.where(ok, (p_group[:, None] * P + slot) * D + doc,
                        T * P * D)
        cube = cube.reshape(-1).at[tgt.ravel()].add(
            jnp.where(ok, pay, jnp.uint32(0)).ravel(), mode="drop"
        ).reshape(T, P, D)
        pv = pv.reshape(-1).at[tgt.ravel()].set(
            ok.ravel(), mode="drop").reshape(T, P, D)
        if stage == 1:
            return cube.sum(axis=(0, 1))[:2 * k2].astype(jnp.float32)

        # min_scores inline, staged
        posscore, posw, wordpos, hg = position_weights(cube, pv)
        present = jnp.any(pv, axis=1)
        if stage == 2:
            return posscore.sum(axis=(0, 1))[:2 * k2]
        mhg = jnp.asarray(weights.MAPPED_HASHGROUP)[hg]
        is_inlink = hg == HASHGROUP_INLINKTEXT
        grp_max = [
            jnp.max(jnp.where(mhg == g, posscore, 0.0), axis=1)
            if g != HASHGROUP_INLINKTEXT else jnp.zeros((T, D),
                                                        posscore.dtype)
            for g in range(HASHGROUP_END)]
        inlink_scores = jnp.where(is_inlink, posscore, 0.0)
        cand = jnp.concatenate(
            [jnp.stack(grp_max, axis=1), inlink_scores], axis=1)
        if stage == 3:
            return cand.sum(axis=(0, 1))[:2 * k2]
        k10 = min(weights.MAX_TOP, cand.shape[1])
        top_sum = jnp.sum(jnp.sort(cand, axis=1)[:, -k10:, :], axis=1)
        single = top_sum * (freqw * freqw)[:, None]
        if stage == 4:
            return single.sum(axis=0)[:2 * k2]
        s_mask = present & counts[:, None]
        min_single = jnp.min(jnp.where(s_mask, single, big), axis=0)
        in_body = jnp.asarray(weights.IN_BODY)[hg]
        min_pair = jnp.full((D,), big)
        any_pair = jnp.zeros((D,), jnp.bool_)
        for i in range(T):
            for j in range(i + 1, T):
                delta = (wordpos[j][None, :, :]
                         - wordpos[i][:, None, :]).astype(jnp.float32)
                d_plain = jnp.maximum(jnp.abs(delta), 2.0)
                body_i = in_body[i][:, None, :]
                body_j = in_body[j][None, :, :]
                mixed = body_i != body_j
                both_nb = (~body_i) & (~body_j)
                d_base = jnp.where(
                    both_nb & (d_plain > weights.NONBODY_DIST_CAP),
                    float(weights.FIXED_DISTANCE), d_plain)
                d_adj = (jnp.where(d_base >= QDIST, d_base - QDIST,
                                   d_base) + (delta < 0))
                dist = jnp.where(mixed, float(weights.FIXED_DISTANCE),
                                 d_adj)
                pvp = (pv[i][:, None, :] & pv[j][None, :, :])
                ps = (weights.BASE_SCORE
                      * posw[i][:, None, :] * posw[j][None, :, :]
                      / (dist + 1.0)) * pvp
                best = jnp.max(ps, axis=(0, 1))
                wts = best * freqw[i] * freqw[j]
                pair_ok = (present[i] & present[j]
                           & counts[i] & counts[j])
                min_pair = jnp.where(pair_ok,
                                     jnp.minimum(min_pair, wts), min_pair)
                any_pair = any_pair | pair_ok
        if stage == 5:
            return min_pair[:2 * k2]
        min_sc = jnp.minimum(jnp.where(any_pair, min_pair, big),
                             min_single)
        min_sc = jnp.where(jnp.any(counts), min_sc, 1.0)
        req_ok = jnp.all(jnp.where(required[:, None], present, True),
                         axis=0)
        neg_ok = ~jnp.any(jnp.where(negative[:, None], present, False),
                          axis=0)
        match = (req_ok & neg_ok & presence_table_ok(present, table)
                 & (jnp.arange(D) < n_docs_total) & (min_sc < big))
        final = jnp.where(
            match, min_sc * final_multipliers(d_siterank, d_doclang,
                                              qlang), 0.0)
        ts, ti = jax.lax.approx_max_k(final, k2, recall_target=0.98)
        return jnp.concatenate([ts, ti.astype(jnp.float32)])

    return jax.vmap(one)(c_slot, c_dslot, c_group, c_base, c_quota,
                         c_syn, p_start, p_len, p_group, p_base, p_quota,
                         p_syn, p_isbase, freqw, required, negative,
                         scored, counts, table, qlang)


def main():
    coll = Collection("bench", os.environ.get("BENCH_DIR", "/root/bench_cache/b100k"))
    di = engine.get_device_index(coll)
    print("ready", flush=True)
    qs = bench._make_queries(3000, seed=33)
    f2_cut = min(dv.CUBE_MIN_DF, max(2 * dv.KAPPA_FLOOR, di.n_docs // 8))
    f2_plans = []
    for q in qs:
        p = di.plan(compile_query(q, 0))
        if p.matchable and p.driver_df > f2_cut:
            f2_plans.append(p)
        if len(f2_plans) >= 8 * 8:
            break
    print(f"{len(f2_plans)} f2 plans; bmax={di._f2_bmax()}", flush=True)
    orig = dv._full_cube
    for stage in range(0, 7):
        dv._full_cube = partial(f2_staged, stage=stage)
        t0 = time.perf_counter()
        jax.block_until_ready(di._run_batch_f2(f2_plans[:8], 64, False))
        c = time.perf_counter() - t0
        times = []
        for i in range(1, 5):
            t0 = time.perf_counter()
            jax.block_until_ready(di._run_batch_f2(
                f2_plans[8 * i:8 * i + 8], 64, False))
            times.append(time.perf_counter() - t0)
        print(f"stage {stage}: {1000*min(times):.0f} ms/chunk8 "
              f"(compile {c:.0f}s)", flush=True)
    dv._full_cube = orig


if __name__ == "__main__":
    main()
