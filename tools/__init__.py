"""Repo tooling — ``python -m tools.osselint`` etc."""
