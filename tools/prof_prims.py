"""Measure TPU primitive throughput: gather variants, scatter, top_k, dense ops."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

rng = np.random.default_rng(0)


def timeit(fn, *args, n=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def report(name, secs, n_elem, bytes_per=4):
    print(f"{name}: {1000*secs:.2f} ms  ({n_elem/secs/1e6:.0f} Melem/s, "
          f"{n_elem*bytes_per/secs/1e9:.1f} GB/s)", file=sys.stderr)


def main():
    print("devices:", jax.devices(), file=sys.stderr)
    N = 1 << 25
    src = jax.device_put(rng.integers(0, 2**31, N, dtype=np.int64).astype(np.int32))

    M = 8_000_000
    idx_rand = jax.device_put(rng.integers(0, N, M).astype(np.int32))
    idx_seq0 = rng.integers(0, N - 4096, M // 4096).astype(np.int32)
    idx_seq = jax.device_put((idx_seq0[:, None] + np.arange(4096, dtype=np.int32)).reshape(-1))

    f_gather = jax.jit(lambda i: src[i].sum())
    report("gather random scalar [8M]", timeit(f_gather, idx_rand), M)
    report("gather contiguous-runs scalar [8M]", timeit(f_gather, idx_seq), M)

    # block gather: reshape source into 128-lane rows, gather rows
    src2 = src.reshape(-1, 128)
    Mb = M // 128
    bidx = jax.device_put(rng.integers(0, N // 128, Mb).astype(np.int32))
    f_block = jax.jit(lambda i: src2[i].sum())
    report("gather random 128-blocks [8M elems]", timeit(f_block, bidx), M)

    src2b = src.reshape(-1, 512)
    Mb2 = M // 512
    bidx2 = jax.device_put(rng.integers(0, N // 512, Mb2).astype(np.int32))
    f_block2 = jax.jit(lambda i: src2b[i].sum())
    report("gather random 512-blocks [8M elems]", timeit(f_block2, bidx2), M)

    # vmapped dynamic_slice (contiguous segments)
    S = 256
    LS = 32768
    starts = jax.device_put(rng.integers(0, N - LS, S).astype(np.int32))
    f_ds = jax.jit(lambda st: jax.vmap(
        lambda s: jax.lax.dynamic_slice(src, (s,), (LS,)).sum())(st))
    report(f"vmapped dynamic_slice [{S}x{LS}]", timeit(f_ds, starts), S * LS)

    # scan of dynamic_slice
    f_scan = jax.jit(lambda st: jax.lax.scan(
        lambda c, s: (c + jax.lax.dynamic_slice(src, (s,), (LS,)).sum(), None),
        jnp.int32(0), st)[0])
    report(f"scan dynamic_slice [{S}x{LS}]", timeit(f_scan, starts), S * LS)

    # scatter random set
    Msc = 2_000_000
    sidx = jax.device_put(rng.integers(0, N, Msc).astype(np.int32))
    vals = jax.device_put(rng.integers(0, 100, Msc).astype(np.int32))
    f_scat = jax.jit(lambda i, v: src.at[i].set(v, mode="drop").sum())
    report("scatter random set [2M]", timeit(f_scat, sidx, vals), Msc)

    # scatter into small dest (cube-like)
    dest_small = jnp.zeros((2048 * 4 * 16,), jnp.int32)
    sidx2 = jax.device_put(rng.integers(0, 2048 * 4 * 16, Msc).astype(np.int32))
    f_scat2 = jax.jit(lambda i, v: dest_small.at[i].set(v, mode="drop").sum())
    report("scatter random set into 131k dest [2M]", timeit(f_scat2, sidx2, vals), Msc)

    # top_k over large minor dim
    B = 32
    D = 131072
    x = jax.device_put(rng.random((B, D), dtype=np.float32))
    f_topk = jax.jit(lambda x: jax.lax.top_k(x, 64)[0].sum())
    report(f"top_k(64) over [{B},{D}]", timeit(f_topk, x), B * D)

    # argsort-based alternative for top-k
    f_sortk = jax.jit(lambda x: jax.lax.sort(x, dimension=1)[:, -64:].sum())
    report(f"full sort over [{B},{D}]", timeit(f_sortk, x), B * D)

    # dense elementwise chain on [B, T, P, D] layout (D minor)
    T, P, Dt = 4, 16, 2048
    cube = jax.device_put(
        rng.integers(0, 2**31, (B, T, P, Dt), dtype=np.int64).astype(np.uint32))

    @jax.jit
    def dense_chain(c):
        wp = (c & jnp.uint32(0x3FFFF)).astype(jnp.int32)
        hg = ((c >> jnp.uint32(18)) & jnp.uint32(0xF)).astype(jnp.int32)
        w = jnp.asarray(np.linspace(0, 1, 16, dtype=np.float32))[hg]
        s = w * w * 1000.0
        m = jnp.max(s, axis=2)
        return m.sum() + wp.sum()

    report(f"dense decode+weight [B,{T},{P},{Dt}] (D minor)", timeit(dense_chain, cube),
           B * T * P * Dt)

    # same chain on [B, D, T, P] layout (P minor=16)
    cube2 = jax.device_put(
        rng.integers(0, 2**31, (B, Dt, T, P), dtype=np.int64).astype(np.uint32))

    @jax.jit
    def dense_chain2(c):
        wp = (c & jnp.uint32(0x3FFFF)).astype(jnp.int32)
        hg = ((c >> jnp.uint32(18)) & jnp.uint32(0xF)).astype(jnp.int32)
        w = jnp.asarray(np.linspace(0, 1, 16, dtype=np.float32))[hg]
        s = w * w * 1000.0
        m = jnp.max(s, axis=3)
        return m.sum() + wp.sum()

    report(f"dense decode+weight [B,{Dt},{T},{P}] (P minor)", timeit(dense_chain2, cube2),
           B * T * P * Dt)

    # pair-score-like cross product [P,P,D] vs [D,P,P]
    wpA = jax.device_put(rng.integers(0, 2**18, (B, P, Dt)).astype(np.int32))

    @jax.jit
    def pair_tpd(wp):
        d = (wp[:, None, :, :] - wp[:, :, None, :]).astype(jnp.float32)
        return jnp.max(1000.0 / (jnp.abs(d) + 1.0), axis=(1, 2)).sum()

    report(f"pair cross [B,{P},{P},{Dt}] (D minor)", timeit(pair_tpd, wpA),
           B * P * P * Dt)

    wpB = jax.device_put(rng.integers(0, 2**18, (B, Dt, P)).astype(np.int32))

    @jax.jit
    def pair_dpp(wp):
        d = (wp[:, :, None, :] - wp[:, :, :, None]).astype(jnp.float32)
        return jnp.max(1000.0 / (jnp.abs(d) + 1.0), axis=(2, 3)).sum()

    report(f"pair cross [B,{Dt},{P},{P}] (P minor)", timeit(pair_dpp, wpB),
           B * P * P * Dt)


if __name__ == "__main__":
    main()
