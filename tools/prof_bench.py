"""Profiling harness for the resident query path (not part of the repo API)."""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

N_DOCS = int(os.environ.get("BENCH_DOCS", "2000"))
N_QUERIES = 96
BATCH = 32

import bench


def main():
    import jax
    print("devices:", jax.devices(), file=sys.stderr)
    from open_source_search_engine_tpu.index.collection import Collection
    from open_source_search_engine_tpu.query import engine

    coll = Collection("bench", tempfile.mkdtemp(prefix="osse_prof_"))
    t0 = time.perf_counter()
    vocab = bench._build_corpus(coll, N_DOCS)
    print(f"build: {time.perf_counter()-t0:.1f}s", file=sys.stderr)
    queries = bench._make_queries(vocab, N_QUERIES)
    batches = [queries[i:i + BATCH] for i in range(0, len(queries), BATCH)]

    di = engine.get_device_index(coll)
    print(f"base doc-runs={len(di.h_doc_col)} docs={di.n_docs}",
          file=sys.stderr)

    # warmup
    for b in batches:
        engine.search_device_batch(coll, b, topk=10, with_snippets=False)

    # plan-only timing
    from open_source_search_engine_tpu.query.compiler import compile_query
    plans = [compile_query(q, 0) for q in queries]
    t0 = time.perf_counter()
    for qp in plans:
        di.plan(qp)
    t_plan = time.perf_counter() - t0
    print(f"plan: {1000*t_plan/len(plans):.2f} ms/query", file=sys.stderr)

    # search_batch timing (includes device)
    t0 = time.perf_counter()
    for b in batches:
        di.search_batch(b, topk=20)
    t_sb = time.perf_counter() - t0
    print(f"search_batch total: {t_sb:.2f}s -> {N_QUERIES/t_sb:.1f} qps",
          file=sys.stderr)

    # full search_device_batch (includes result building)
    t0 = time.perf_counter()
    for b in batches:
        engine.search_device_batch(coll, b, topk=10, with_snippets=False)
    t_f = time.perf_counter() - t0
    print(f"full batch: {t_f:.2f}s -> {N_QUERIES/t_f:.1f} qps", file=sys.stderr)

    # single-query latency
    t0 = time.perf_counter()
    for q in queries[:20]:
        engine.search_device(coll, q, topk=10, with_snippets=False)
    lat = (time.perf_counter() - t0) / 20
    print(f"single-query: {1000*lat:.1f} ms", file=sys.stderr)

    # shape-bucket distribution
    from collections import Counter

    from open_source_search_engine_tpu.query.devindex import (
        LSP_FLOOR, RD_FLOOR, RS_FLOOR)
    from open_source_search_engine_tpu.query.packer import _bucket
    c = Counter()
    for qp in plans:
        p = di.plan(qp)
        if not p.matchable:
            c["unmatchable"] += 1
            continue
        c[(_bucket(max(len(p.d_slot), 1), RD_FLOOR),
           _bucket(max(len(p.s_start), 1), RS_FLOOR),
           _bucket(int(p.s_len.max()) if len(p.s_len) else 1,
                   LSP_FLOOR))] += 1
    print("shape buckets (Rd,Rs,Lsp):", dict(c), file=sys.stderr)
    print(f"escalations: {di.escalations}", file=sys.stderr)


if __name__ == "__main__":
    main()
