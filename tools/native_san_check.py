"""Sanitizer parity driver — run the native C++ cores under ASan+UBSan.

Builds ``librdbcore.san.so`` / ``libdoccore.san.so`` (OSSE_NATIVE_SAN=1
artifacts, ``-fsanitize=address,undefined``) and drives the same parity
checks the tier-1 native tests run — merge/searchsorted vs. the numpy
reference, tokenize/hash vs. the Python tokenizer — so any heap
overflow, use-after-free, or UB in ``rdbcore.cpp``/``doccore.cpp``
aborts loudly instead of corrupting an index silently.

The sanitizer runtimes must be loaded BEFORE an uninstrumented Python:
when launched without them this script re-execs itself with
``LD_PRELOAD=libasan.so:libubsan.so`` (paths from
``g++ -print-file-name``) and ``ASAN_OPTIONS=detect_leaks=0`` (CPython
itself "leaks" interned objects at exit; leak mode would drown real
reports).

Deliberately jax-free: only numpy + the host-plane modules import, so
the whole check runs in a couple of seconds.

Usage::

    python -m tools.native_san_check          # re-execs under preload
    OSSE_NATIVE_SAN=1 pytest tests/test_native.py -m slow   # via test
"""

from __future__ import annotations

import os
import subprocess
import sys


def _sanitizer_libs() -> list[str]:
    libs = []
    for name in ("libasan.so", "libubsan.so"):
        out = subprocess.run(["g++", f"-print-file-name={name}"],
                             capture_output=True, text=True,
                             check=False).stdout.strip()
        if out and out != name and os.path.exists(out):
            libs.append(out)
    return libs


def _reexec_under_preload() -> None:
    libs = _sanitizer_libs()
    if not libs:
        print("native_san_check: no sanitizer runtimes found "
              "(g++ -print-file-name) — cannot run", file=sys.stderr)
        sys.exit(2)
    env = dict(os.environ)
    env["LD_PRELOAD"] = ":".join(libs)
    env["OSSE_NATIVE_SAN"] = "1"
    env.setdefault("ASAN_OPTIONS", "detect_leaks=0")
    os.execve(sys.executable,
              [sys.executable, "-m", "tools.native_san_check"], env)


def main() -> int:
    if "libasan" not in os.environ.get("LD_PRELOAD", ""):
        _reexec_under_preload()  # never returns

    os.environ["OSSE_NATIVE_SAN"] = "1"
    import numpy as np

    from open_source_search_engine_tpu import native
    from open_source_search_engine_tpu.index import posdb, rdblite

    assert native.SANITIZE, "OSSE_NATIVE_SAN=1 not honored at import"
    if native.get_lib() is None:
        print("native_san_check: sanitized rdbcore build failed",
              file=sys.stderr)
        return 2

    rng = np.random.default_rng(7)

    def random_run(n, seed):
        r = np.random.default_rng(seed)
        keys = posdb.pack(
            termid=r.integers(0, 60, n), docid=r.integers(0, 300, n),
            wordpos=r.integers(0, 2000, n),
            delbit=(r.random(n) > 0.25).astype(int))
        return keys[rdblite.key_sort_order(keys)]

    checks = 0

    # --- rdbcore: n-way merge parity (both tombstone modes) ------------
    runs = [random_run(int(rng.integers(50, 600)), s)
            for s in range(5)]
    for keep in (False, True):
        nat = native.merge_runs(runs, keep)
        assert nat is not None, "sanitized merge_runs unavailable"
        all_keys = np.concatenate(runs)
        recency = np.concatenate(
            [np.full(len(r), i, np.int64) for i, r in enumerate(runs)])
        ref = all_keys[rdblite._dedup_newest(all_keys, recency, keep)]
        assert len(nat) == len(ref), \
            f"merge length {len(nat)} != {len(ref)} (keep={keep})"
        np.testing.assert_array_equal(
            nat.view(np.uint8).reshape(-1),
            ref.view(np.uint8).reshape(-1))
        checks += 1

    # --- rdbcore: searchsorted parity ----------------------------------
    keys = random_run(800, 99)
    probes = random_run(64, 100)
    for side in ("left", "right"):
        nat = np.array([native.searchsorted(keys, probes[i:i + 1], side)
                        for i in range(len(probes))])
        orig_avail = native.available
        native.available = lambda: False
        try:
            ref = rdblite.searchsorted_keys(keys, probes, side)
        finally:
            native.available = orig_avail
        np.testing.assert_array_equal(nat, ref)
        checks += 1

    # --- doccore: tokenize + hash parity -------------------------------
    if native.get_doccore() is None:
        print("native_san_check: sanitized doccore build failed",
              file=sys.stderr)
        return 2
    from open_source_search_engine_tpu.build import tokenizer
    from open_source_search_engine_tpu.utils import ghash
    html = ("<html><head><title>Sanitizer parity</title>"
            "<meta name=\"description\" content=\"asan ubsan\"></head>"
            "<body><h1>Heading words</h1><p>Body text with "
            "<a href=\"http://example.com/x\">anchor text</a> and "
            "repeated repeated terms.</p>"
            "<script>ignored()</script></body></html>")
    url = "http://example.com/parity"
    os.environ["OSSE_NATIVE_TOKENIZE"] = "0"
    try:
        py = tokenizer.tokenize_html(html, url)
    finally:
        os.environ["OSSE_NATIVE_TOKENIZE"] = "1"
    nat_doc = tokenizer.tokenize_html(html, url)
    cols = getattr(nat_doc, "native", None)
    assert cols is not None, "native tokenize fell back"
    assert py.words == nat_doc.words, "word parity under sanitizers"
    assert py.wordpos == nat_doc.wordpos, \
        "wordpos parity under sanitizers"
    assert py.hashgroups == nat_doc.hashgroups, \
        "hashgroup parity under sanitizers"
    tids = [ghash.term_id(w) for w in nat_doc.words]
    assert tids == [int(t) for t in cols.termid], \
        "termid parity under sanitizers"
    checks += 1
    # ghash.hash64 switches to blake2b above 1 KiB; native parity is
    # the short-key (FNV+avalanche) regime only
    for blob in (b"", b"a", b"hello world", b"ab\x00cd",
                 bytes(range(256)) * 4):
        nat = native.hash64_native(blob)
        assert nat == ghash.hash64(blob), f"hash64 parity: {blob[:8]!r}"
    checks += 1

    print(f"native_san_check: OK ({checks} parity checks clean under "
          "ASan+UBSan)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
