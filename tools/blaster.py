"""Blaster — query replay / load test / cluster diff against live
``/search`` endpoints.

Reference: ``gb blaster`` replays a query (or url) file in parallel
against a cluster (``Blaster.h:31``, ``main.cpp:1861``) and
``blasterdiff`` (``main.cpp:1898``) fires each query at TWO clusters
and reports result differences side by side — the tool the reference
uses when "Changing Live Clusters" (developer.html §F.5). This is the
validation instrument for perf claims outside the synthetic bench.

Usage::

    python tools/blaster.py QUERYFILE http://host:8000 \
        [--qps 10] [--n 10] [--threads 8] [--max 1000] [--format json]
    python tools/blaster.py QUERYFILE http://a:8000 --diff http://b:8000

QUERYFILE: one query per line (# comments skipped). Prints a JSON
summary line (qps achieved, latency percentiles, error count; in diff
mode also per-query result mismatches).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.parse
import urllib.request
from concurrent.futures import ThreadPoolExecutor


def _search(base: str, q: str, n: int, timeout: float) -> dict:
    url = (f"{base}/search?format=json&n={n}&q="
           + urllib.parse.quote_plus(q))
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.load(r)


def _load_queries(path: str, limit: int | None) -> list[str]:
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.append(line)
            if limit and len(out) >= limit:
                break
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("queryfile")
    ap.add_argument("endpoint", help="http://host:port of /search")
    ap.add_argument("--diff", metavar="ENDPOINT2",
                    help="second endpoint: compare results per query "
                         "(blasterdiff)")
    ap.add_argument("--qps", type=float, default=0.0,
                    help="target request rate (0 = as fast as the "
                         "thread pool allows)")
    ap.add_argument("--n", type=int, default=10, help="results per query")
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--max", type=int, default=0,
                    help="replay at most this many queries (0 = all)")
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)

    queries = _load_queries(args.queryfile, args.max or None)
    if not queries:
        print("no queries", file=sys.stderr)
        return 2

    lats: list[float] = []
    errors = [0]
    diffs: list[dict] = []
    lock = threading.Lock()

    def one(q: str) -> None:
        t0 = time.perf_counter()
        try:
            a = _search(args.endpoint, q, args.n, args.timeout)
        except Exception as e:  # noqa: BLE001 — network errors are data
            with lock:
                errors[0] += 1
            print(f"# ERROR {q!r}: {e}", file=sys.stderr)
            return
        dt = 1000 * (time.perf_counter() - t0)
        with lock:
            lats.append(dt)
        if args.diff:
            try:
                b = _search(args.diff, q, args.n, args.timeout)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors[0] += 1
                print(f"# ERROR(B) {q!r}: {e}", file=sys.stderr)
                return
            ua = [r["url"] for r in a.get("results", [])]
            ub = [r["url"] for r in b.get("results", [])]
            if ua != ub or a.get("totalMatches") != b.get("totalMatches"):
                with lock:
                    diffs.append({
                        "q": q,
                        "totalA": a.get("totalMatches"),
                        "totalB": b.get("totalMatches"),
                        "onlyA": [u for u in ua if u not in ub][:5],
                        "onlyB": [u for u in ub if u not in ua][:5],
                    })

    t0 = time.perf_counter()
    interval = 1.0 / args.qps if args.qps > 0 else 0.0
    with ThreadPoolExecutor(args.threads) as pool:
        for i, q in enumerate(queries):
            if interval:
                # rate pacing: schedule each request at its slot
                target = t0 + i * interval
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            pool.submit(one, q)
    elapsed = time.perf_counter() - t0

    lats.sort()
    pct = lambda p: round(lats[int(p * (len(lats) - 1))], 1) \
        if lats else None
    out = {
        "queries": len(queries),
        "ok": len(lats),
        "errors": errors[0],
        "elapsed_s": round(elapsed, 2),
        "qps": round(len(lats) / elapsed, 2) if elapsed else 0,
        "p50_ms": pct(0.50), "p90_ms": pct(0.90), "p99_ms": pct(0.99),
    }
    if args.diff:
        out["diffs"] = len(diffs)
        for d in diffs[:20]:
            print("# DIFF " + json.dumps(d), file=sys.stderr)
    print(json.dumps(out))
    return 0 if not errors[0] and not (args.diff and diffs) else 1


if __name__ == "__main__":
    sys.exit(main())
