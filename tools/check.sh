#!/usr/bin/env bash
# One-command PR gate: tree-wide lint, fixture sanity, fast tier-1
# slice. Builders and future PRs run this instead of remembering the
# pieces; tests/test_lint.py invokes `check.sh --lint-only` so the
# gate itself stays tested (the flag stops before pytest — otherwise
# the gate would recurse into the test that runs it).
set -euo pipefail
cd "$(dirname "$0")/.."

# 1. the whole tree must be invariant-clean
python -m tools.osselint

# 2. fixture sanity via the CLI: clean fixtures lint clean, violation
#    fixtures actually produce findings (the exact-line marker match
#    lives in tests/test_lint.py)
python -m tools.osselint tests/lint_fixtures/clean_parallel.py \
    tests/lint_fixtures/clean_jit.py tests/lint_fixtures/clean_mesh.py \
    tests/lint_fixtures/clean_tenancy.py \
    tests/lint_fixtures/clean_devbuild.py \
    tests/lint_fixtures/clean_statsname.py \
    tests/lint_fixtures/clean_sched.py
for f in tests/lint_fixtures/violations_*.py; do
    if python -m tools.osselint "$f" > /dev/null 2>&1; then
        echo "check.sh: $f produced no findings" >&2
        exit 1
    fi
done

if [ "${1:-}" = "--lint-only" ]; then
    echo "check.sh: lint gate OK"
    exit 0
fi

# 3. fast tier-1 slice: the lint gate, the jit plane, the query
#    stack (the layers a typical PR touches), and the seeded chaos
#    smoke — deterministic fault schedules, deadline propagation, twin
#    failover; the full soak gate stays behind `-m slow` / BENCH_SOAK=1
JAX_PLATFORMS=cpu python -m pytest tests/test_lint.py \
    tests/test_jitwatch.py tests/test_query.py tests/test_chaos.py \
    tests/test_statsplane.py tests/test_devwatch.py \
    tests/test_schedcheck.py \
    -q -m 'not slow' -p no:cacheprovider

# 3b. schedule exploration: the five protocol scenario suites plus the
#     seeded historical-bug regressions under the armed explorer — 64
#     seeded interleavings per suite, deterministic and replayable
#     (the 1024-schedule deep run lives behind BENCH_SCHED=1 / -m slow)
OSSE_SCHED=1 OSSE_SCHED_BUDGET=64 JAX_PLATFORMS=cpu \
    python -m pytest tests/test_schedcheck.py \
    -q -m 'not slow' -p no:cacheprovider

# 4. SLO gate: 2-node fleet, mergeable-histogram scrape, burn-rate
#    math; exits nonzero unless the merged histogram is populated,
#    the burn math is finite, and scrape overhead stays under 1% of
#    query wall time
BENCH_SLO=1 JAX_PLATFORMS=cpu python bench.py

# 5. admission smoke: a SHORT open-loop sweep at low qps (generous
#    latency bounds — this is a CI box, not a perf rig); exits nonzero
#    unless overload sheds tier-correctly, every shed is counted, and
#    the queue drains post-burst (bench.py main_load docstring)
BENCH_LOAD=1 BENCH_LOAD_QPS=6,12 BENCH_LOAD_SECONDS=2 \
    BENCH_LOAD_P99_MS=2000 BENCH_LOAD_OVER_P99_MS=3000 \
    JAX_PLATFORMS=cpu python bench.py

# 6. fleet smoke: 2×2 REAL node processes under open-loop load —
#    wedge→SIGKILL a primary with the twin absorbing every query
#    (hedge fired+won, zero lost), journal-replay rejoin, rolling
#    restart through the admission gate, live parm broadcast, and the
#    2→3 cross-process shard split; exits nonzero unless every gate
#    holds and no child process survives teardown
BENCH_FLEET=1 BENCH_FLEET_SECONDS=5 BENCH_FLEET_QPS=8 \
    JAX_PLATFORMS=cpu python bench.py

# 7. tenant smoke: a SHORT Zipf sweep over 64 collections with a
#    32-slot residency budget — gates the hot-set residency hit rate,
#    bounded post-compile cold starts, zero membudget refusals, and
#    weighted-fair quotas keeping a quiet tenant shed-free under a
#    flood (bench.py main_tenants docstring; the 1k-collection shape
#    runs nightly via BENCH_TENANTS=1 defaults)
BENCH_TENANTS=1 BENCH_TENANTS_COLLS=64 BENCH_TENANTS_HOT=32 \
    BENCH_TENANTS_QUERIES=300 \
    JAX_PLATFORMS=cpu python bench.py

# 8. mesh serving smoke: a SHORT scale curve of the in-jit Msg3a merge
#    (subprocess per point, forced host devices) — gates the 4-shard
#    in-jit merge's speedup over the single-chip path on the same
#    corpus, zero compiles/retraces/off-boundary transfers across
#    varying-batch steady-state mesh waves, and twin failover with
#    zero lost queries (bench.py main_mesh docstring; full sizes run
#    nightly via BENCH_MESH=1 defaults)
BENCH_MESH=1 BENCH_MESH_SHARDS=1,4 BENCH_MESH_DPS=80 \
    BENCH_MESH_QUERIES=32 BENCH_MESH_JIT_WAVES=24 \
    BENCH_MESH_FAILOVER_DOCS=60 \
    JAX_PLATFORMS=cpu python bench.py

# 9. ingest-plane smoke: a SMALL corpus through the device posting
#    sort/dedup/pack pipeline — gates bitwise parity against the host
#    oracle (columns, dir tables, f16 impacts), a cold device rebuild
#    under a CI-box bound, and zero compiles/retraces across repeated
#    same-bucket delta folds (bench.py main_build docstring; the
#    100k-doc < 60 s shape runs nightly via BENCH_BUILD=1 defaults)
BENCH_BUILD=1 BENCH_BUILD_DOCS=400 BENCH_BUILD_PARITY_DOCS=200 \
    BENCH_BUILD_REBUILD_S=300 \
    JAX_PLATFORMS=cpu python bench.py

# 10. device-telemetry smoke: the backend doctor (rc=2 "no
#     accelerator" is benign on CI boxes; rc=1 means a TPU host is
#     misbehaving — init-failed or silent CPU fallback), then the
#     devwatch gate — <2% steady-state overhead with the plane armed,
#     HBM ledger == the index's own accounting (and memory_stats
#     within 5% where the backend reports it), a roofline entry per
#     dispatched shape bucket, the doctor stamp on the JSON line
#     (bench.py main_devobs docstring)
JAX_PLATFORMS=cpu python -m tools.devdoctor || [ $? -eq 2 ]
BENCH_DEVOBS=1 BENCH_DEVOBS_DOCS=160 BENCH_DEVOBS_WAVES=40 \
    JAX_PLATFORMS=cpu python bench.py

# 11. concurrency smoke: the schedule-exploration gate at a SHORT
#     budget (the nightly deep run uses the 1024-schedule default) —
#     exits nonzero on any schedule failure, printing the failing seed
#     and shrunk preemption trace (bench.py main_sched docstring)
BENCH_SCHED=1 BENCH_SCHED_SCHEDULES=64 \
    JAX_PLATFORMS=cpu python bench.py
echo "check.sh: OK"
